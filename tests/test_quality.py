"""Inference quality observatory (obs/quality.py, ISSUE 20).

The pins, in dependency order:

1. **One scoring implementation** — the offline CLI's scoring
   functions ARE the observatory's (identity, not equality), and a
   live-scored card equals the CLI's math over the same spans.
2. **Scorecard conservation** — registered == scored +
   expired_unscorable + pending across window advance, event-time TTL
   expiry, bounded-pending eviction, and a kill+resume restart that
   scores via the history tier (cards ride the checkpoint extras).
3. **Closed anomaly reason set** — an unknown reason raises; the
   ledger never silently bins a new detector.
4. **Knob-off byte identity** — HEATMAP_QUALITY=0 runs byte-identical
   to a pre-quality build (tiles, positions, conservation counters,
   view state, forecast response bytes), and knob-ON is observe-only:
   the same surfaces stay identical while scorecards accrue.
5. **Drift → incident** — a skill collapse burns the lower-is-worse
   SLO (op="lt"), claims exactly ONE correlated episode, dumps a
   calibration-enriched flight record, and recovery clears it;
   /healthz naming carries (grid, reducer, shard).
6. **Surfaces** — member block / fleet stitch naming the worst shard,
   obs_top rows, bench provenance stamps + check_bench_regress
   refusals and the live-skill ratchet.
"""

import copy
import datetime as dt
import importlib.util
import json
import os

import numpy as np

from heatmap_tpu import hexgrid
from heatmap_tpu.config import load_config
from heatmap_tpu.obs import quality as qmod
from heatmap_tpu.obs.quality import (QualityObservatory, parse_nis_band,
                                     quality_enabled, quality_stamp,
                                     score_maps)
from heatmap_tpu.obs.registry import Registry
from heatmap_tpu.query import TileMatView
from heatmap_tpu.sink import MemoryStore
from heatmap_tpu.sink.base import TileDoc, UTC
from heatmap_tpu.stream import MemorySource, MicroBatchRuntime

BASE = 1_754_000_000                      # fixed event-time anchor
H = 120.0                                 # forecast horizon under test
CELLS = []
for _i in range(12):
    _c = hexgrid.latlng_to_cell(42.30 + _i * 7e-3, -71.05, 8)
    _c = int(_c, 16) if isinstance(_c, str) else int(_c)
    if _c not in CELLS:
        CELLS.append(_c)
C0, C1 = CELLS[0], CELLS[1]


def _load_tool(name):
    repo = os.path.abspath(os.path.join(os.path.dirname(__file__),
                                        os.pardir))
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(repo, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _doc(cell, ws_epoch, count):
    ws = dt.datetime.fromtimestamp(ws_epoch, UTC)
    return TileDoc("bos", 8, format(int(cell), "x"), ws,
                   ws + dt.timedelta(minutes=5), count=count,
                   avg_speed_kmh=30.0, avg_lat=42.3, avg_lon=-71.05,
                   ttl_minutes=10 ** 6, grid="h3r8")


def _qcfg(**kw):
    kw.setdefault("quality", True)
    kw.setdefault("quality_lookback_s", 60.0)
    kw.setdefault("quality_mature_s", 60.0)
    kw.setdefault("quality_ttl_s", 600.0)
    return load_config({}, **kw)


def _view(windows):
    """A live view holding {ws_epoch: {cell: count}} windows."""
    v = TileMatView()
    for ws, counts in windows.items():
        v.apply_docs([_doc(c, ws, n) for c, n in counts.items()])
    return v


# --------------------------------------------------- knob & band parsing
def test_knob_and_band_parsing():
    assert quality_enabled({}) is False
    assert quality_enabled({"HEATMAP_QUALITY": "0"}) is False
    assert quality_enabled({"HEATMAP_QUALITY": "1"}) is True
    assert parse_nis_band({}) == qmod.DEFAULT_NIS_BAND
    assert parse_nis_band({"HEATMAP_SLO_NIS_BAND": "0.9,0.99"}) \
        == (0.9, 0.99)
    # malformed bands fall back, never raise (observe-only tier)
    for bad in ("backwards", "0.99,0.9", "1.5,2.0", "0.9"):
        assert parse_nis_band({"HEATMAP_SLO_NIS_BAND": bad}) \
            == qmod.DEFAULT_NIS_BAND


# --------------------------------------------- one scoring implementation
def test_offline_cli_is_the_live_scorer():
    sf = _load_tool("score_forecast")
    # the CLI re-exports the observatory's functions — the same object,
    # so the two CANNOT diverge
    assert sf.score_maps is qmod.score_maps
    assert sf.features_to_counts is qmod.features_to_counts
    assert sf.normalize is qmod.normalize
    assert sf.mae is qmod.mae


def test_live_scored_card_equals_offline_cli_math():
    target = int(BASE + H)
    persist = {C0: 5, C1: 5}
    actual = {C0: 8, C1: 2}
    view = _view({BASE - 30: persist, target - 30: actual})
    obs = QualityObservatory(_qcfg(), view=view, tag="s0")
    forecast = {C0: 7.0, C1: 3.0}
    obs.register_forecast(8, H, BASE, forecast)
    assert obs.identity() == {"registered": 1, "scored": 0,
                              "expired_unscorable": 0, "pending": 1,
                              "ok": True}
    obs.mature(target + 60)
    ident = obs.identity()
    assert ident["scored"] == 1 and ident["ok"]
    # the differential: the live score IS the CLI's score_maps over the
    # same hex-keyed maps (the /api/tiles/range aggregate semantics)
    hx = {format(int(c), "x"): float(n) for c, n in forecast.items()}
    expect = score_maps(
        hx, {format(int(c), "x"): float(n) for c, n in persist.items()},
        {format(int(c), "x"): float(n) for c, n in actual.items()})
    assert obs._last_score["skill_vs_persistence"] \
        == expect["skill_vs_persistence"] == 0.6667
    assert obs._last_score["mae_forecast"] == expect["mae_forecast"]


# ---------------------------------------------- scorecard conservation
def test_conservation_window_advance_ttl_and_bounded_pending(
        monkeypatch):
    target = int(BASE + H)
    view = _view({BASE - 30: {C0: 5, C1: 5},
                  target - 30: {C0: 8, C1: 2}})
    reg = Registry()
    obs = QualityObservatory(_qcfg(), registry=reg, view=view, tag="s0")
    # two horizons: H (answerable) and a far one whose target span the
    # view will never hold (unscorable)
    obs.register_forecast(8, H, BASE, {C0: 7.0, C1: 3.0})
    obs.register_forecast(8, 10_000.0, BASE, {C0: 7.0})
    assert obs.identity()["pending"] == 2 and obs.identity()["ok"]
    # window advance: not yet mature — nothing moves
    obs.mature(target + 30)
    assert obs.identity()["pending"] == 2 and obs.identity()["ok"]
    # first card matures and scores; the far one stays pending
    obs.mature(target + 60)
    assert obs.identity() == {"registered": 2, "scored": 1,
                              "expired_unscorable": 0, "pending": 1,
                              "ok": True}
    # fake-clock eviction: the far card matures with an EMPTY span and
    # re-pends until the event-time TTL calls it unscorable — a
    # function of the event stream, never the wall clock
    far_target = int(BASE + 10_000)
    obs.mature(far_target + 60)
    assert obs.identity()["pending"] == 1      # re-pended, not dropped
    obs.mature(far_target + 600)               # past quality_ttl_s
    assert obs.identity() == {"registered": 2, "scored": 1,
                              "expired_unscorable": 1, "pending": 0,
                              "ok": True}
    # the counter family carries the same ledger the identity checks
    snap = reg.expose_text()
    assert 'heatmap_quality_scorecards_total{outcome="scored"} 1' \
        in snap
    assert ('heatmap_quality_scorecards_total'
            '{outcome="expired_unscorable"} 1') in snap
    # bounded pending: past MAX_PENDING the OLDEST card leaves as
    # expired_unscorable — accounted, never silently dropped
    monkeypatch.setattr(qmod, "MAX_PENDING", 2)
    for _ in range(4):
        obs.register_forecast(8, H, BASE, {C0: 1.0})
    ident = obs.identity()
    assert ident["pending"] == 2
    assert ident["expired_unscorable"] == 3 and ident["ok"]


def test_kill_resume_scores_via_history_tier(tmp_path):
    """A card registered before a kill scores AFTER the restart from
    the history tier: the pending set rides the checkpoint extras and
    the restored observatory reads the compacted chunks (no live view
    needed)."""
    import tempfile

    from heatmap_tpu.obs.audit import DigestTable
    from heatmap_tpu.query.history import HistoryCompactor, HistoryLog
    from heatmap_tpu.query.repl import DeltaLogPublisher

    target = int(BASE + H)
    # the clock anchors retention: chunks are pruned relative to "now",
    # so the fake clock sits just past the event-time windows
    clock = {"t": float(BASE + 900)}
    feed = tempfile.mkdtemp(dir=str(tmp_path))
    hist = tempfile.mkdtemp(dir=str(tmp_path))
    w = TileMatView(now_fn=lambda: clock["t"])
    w.audit_table = DigestTable()
    pub = DeltaLogPublisher(w, feed, start=False, hist=HistoryLog(hist))
    for ws, counts in ((BASE - 30, {C0: 5, C1: 5}),
                       (target - 30, {C0: 8, C1: 2})):
        w.apply_docs([_doc(c, ws, n) for c, n in counts.items()])
        pub.flush()
    # "process 1": register against the live view, then die before the
    # card matures
    obs1 = QualityObservatory(_qcfg(), view=w, tag="s0")
    obs1.register_forecast(8, H, BASE, {C0: 7.0, C1: 3.0})
    blob = obs1.snapshot_extra()
    assert blob["state"].dtype == np.uint8      # checkpoint-extra shape
    pub.close()
    comp = HistoryCompactor(hist, feed_dir=feed,
                            clock=lambda: clock["t"])
    assert comp.step() > 0
    # "process 2": NO view — only the compacted history tier
    reg = Registry()
    obs2 = QualityObservatory(_qcfg(hist_dir=hist), registry=reg,
                              view=None, tag="s0")
    assert obs2.restore_extra(blob) == 1
    assert obs2.identity() == {"registered": 1, "scored": 0,
                               "expired_unscorable": 0, "pending": 1,
                               "ok": True}
    obs2.mature(target + 60)
    assert obs2.identity()["scored"] == 1 and obs2.identity()["ok"]
    assert obs2._last_score["skill_vs_persistence"] == 0.6667
    assert 'heatmap_quality_forecast_skill{grid="h3r8",h="120"} 0.6667' \
        in reg.expose_text()
    # a corrupt blob starts cold instead of raising
    bad = {"state": np.frombuffer(b"not json", dtype=np.uint8)}
    assert QualityObservatory(_qcfg(), tag="x").restore_extra(bad) == 0


# ------------------------------------------------- closed anomaly reasons
def test_anomaly_reason_set_is_pinned_closed():
    from heatmap_tpu.infer.engine import ANOMALY_REASONS

    assert ANOMALY_REASONS == ("stopped", "teleport", "deviation")
    obs = QualityObservatory(_qcfg(), tag="s0")
    kw = dict(t=BASE, updates=10, inside=9, inn_n=1.0, inn_e=1.0,
              table={})
    obs.note_fold(anomalies={"teleport": 3, "stopped": 1}, **kw)
    try:
        obs.note_fold(anomalies={"teleport": 4, "wormhole": 1}, **kw)
    except ValueError as e:
        assert "wormhole" in str(e) and "closed" in str(e)
    else:
        raise AssertionError("unknown anomaly reason must raise")


# --------------------------------------------- calibration & /healthz
def test_calibration_window_healthz_naming_and_recovery():
    target = int(BASE + H)
    # a BAD forecast (inverted shape) so the scored skill goes negative
    view = _view({BASE - 30: {C0: 5, C1: 5},
                  target - 30: {C0: 8, C1: 2}})
    obs = QualityObservatory(_qcfg(quality_window_s=100.0), view=view,
                             tag="shard3")
    obs.register_forecast(8, H, BASE, {C0: 1.0, C1: 9.0})
    obs.mature(target + 60)
    # miscalibrated fold stream: coverage 0.5, far below the band
    obs.note_fold(t=BASE, updates=100, inside=50, inn_n=200.0,
                  inn_e=0.0, anomalies={"teleport": 2},
                  table={"entities": 10, "capacity": 100,
                         "evicted_ttl": 0, "evicted_lru": 0,
                         "reseed_handoff": 0, "reseed_teleport": 0})
    obs.note_fold(t=BASE + 50, updates=100, inside=50, inn_n=200.0,
                  inn_e=0.0, anomalies={"teleport": 6},
                  table={"entities": 12, "capacity": 100,
                         "evicted_ttl": 1, "evicted_lru": 3,
                         "reseed_handoff": 0, "reseed_teleport": 2})
    checks, degraded = obs.healthz_checks()
    assert degraded
    cov = checks["quality_nis_coverage"]
    assert cov["ok"] is False and cov["value"] == 0.5
    assert "reducer=kalman" in cov["detail"]
    assert "shard=shard3" in cov["detail"]
    sk = checks["quality_forecast_skill"]
    assert sk["ok"] is False and sk["value"] < 0
    assert "grid=h3r8" in sk["detail"] and "h=120" in sk["detail"]
    assert "shard=shard3" in sk["detail"]
    # the member block carries the same picture for /fleet/quality
    blk = obs.member_block()
    assert blk["enabled"] and blk["nis"]["coverage"] == 0.5
    assert blk["nis"]["band_error"] > 0
    assert blk["skill"]["h3r8|120"] < 0
    assert blk["anomaly_rate"]["teleport"] == round(6 / 50, 4)
    assert blk["table"]["occupancy"] == 12
    assert blk["table"]["lru_evict_frac"] == 0.75
    # recovery: the rolling window advances past the bad folds and a
    # calibrated stream clears the coverage check
    for i in (200, 260):
        obs.note_fold(t=BASE + i, updates=100, inside=95, inn_n=0.0,
                      inn_e=0.0, anomalies={"teleport": 6}, table={})
    checks, _ = obs.healthz_checks()
    assert checks["quality_nis_coverage"]["ok"] is True
    # the snapshot (flightrec source) adds the last score + pending tail
    snap = obs.snapshot()
    assert snap["last_score"]["skill_vs_persistence"] < 0
    assert snap["pending_tail"] == []


# -------------------------------------------------- knob-off differential
def _mk_stream():
    rng = np.random.default_rng(7)
    pos = {v: (42.3 + 0.1 * rng.random(), -71.1 + 0.1 * rng.random())
           for v in range(17)}
    out = []
    for i in range(3 * 128):
        v = i % 17
        la, lo = pos[v]
        pos[v] = (la + 6e-5, lo - 6e-5)
        out.append({"provider": "mbta", "vehicleId": f"veh-{v}",
                    "lat": la, "lon": lo, "speedKmh": 25.0,
                    "bearing": 0.0, "accuracyM": 5.0,
                    "ts": BASE + 5 * (i // 17)})
    return out


def _run_rt(tmp_path, events, store, tag, view, quality):
    cfg = load_config(
        {}, batch_size=128, state_capacity_log2=10, speed_hist_bins=8,
        store="memory", reducers=("count", "kalman"), quality=quality,
        quality_lookback_s=60.0,
        checkpoint_dir=str(tmp_path / f"ckpt-{tag}"))
    src = MemorySource(copy.deepcopy(events))
    src.finish()
    rt = MicroBatchRuntime(cfg, src, store, checkpoint_every=0,
                           view=view)
    rt.run()
    return rt


def _get(app, path, query=""):
    out = {}

    def start_response(status, headers):
        out["status"] = status

    body = b"".join(app({"PATH_INFO": path, "REQUEST_METHOD": "GET",
                         "QUERY_STRING": query}, start_response))
    return out["status"], body


def test_knob_off_byte_identity_and_observe_only_registration(tmp_path):
    from heatmap_tpu.serve.api import make_wsgi_app

    events = _mk_stream()
    off_store, on_store = MemoryStore(), MemoryStore()
    off_view, on_view = TileMatView(), TileMatView()
    rt_off = _run_rt(tmp_path, events, off_store, "off", off_view,
                     quality=False)
    rt_on = _run_rt(tmp_path, events, on_store, "on", on_view,
                    quality=True)
    assert rt_off.quality is None and rt_on.quality is not None
    # tiles, positions, conservation counters: byte-identical — the
    # observatory observes the fold, it never touches it
    assert off_store._tiles == on_store._tiles
    assert off_store._positions == on_store._positions
    keys = ("events_valid", "events_invalid", "events_late", "batches",
            "tiles_emitted", "positions_emitted")
    s_off, s_on = rt_off.metrics.snapshot(), rt_on.metrics.snapshot()
    assert {k: s_off.get(k) for k in keys} \
        == {k: s_on.get(k) for k in keys}
    # view state: same seqs, same windows, same docs
    assert off_view.export_state() == on_view.export_state()
    # exposition: knob-off registers NO quality family at all
    assert "heatmap_quality_" not in rt_off.metrics.registry \
        .expose_text()
    assert "heatmap_quality_nis_coverage" in rt_on.metrics.registry \
        .expose_text()
    # the forecast RESPONSE is byte-identical too, while knob-on
    # registration accrues scorecards behind it (observe-only)
    app_off = make_wsgi_app(off_store, rt_off.cfg, runtime=rt_off)
    app_on = make_wsgi_app(on_store, rt_on.cfg, runtime=rt_on)
    st_off, b_off = _get(app_off, "/api/tiles/forecast", "h=120")
    st_on, b_on = _get(app_on, "/api/tiles/forecast", "h=120")
    assert st_off.startswith("200") and st_off == st_on
    assert b_off == b_on
    ident = rt_on.quality.identity()
    assert ident["registered"] == 1 and ident["ok"]
    # /debug/quality: the live snapshot knob-on, 503 knob-off
    st, body = _get(app_on, "/debug/quality")
    assert st.startswith("200")
    assert json.loads(body)["scorecards"]["registered"] == 1
    st, _ = _get(app_off, "/debug/quality")
    assert st.startswith("503")


# ------------------------------------------------------ drift -> incident
def test_skill_drift_burns_one_episode_with_enriched_flightrec(
        tmp_path):
    from heatmap_tpu.obs.flightrec import FlightRecorder
    from heatmap_tpu.obs.slo import BurnRule, SloEngine, default_specs
    from heatmap_tpu.obs.tsdb import TsdbRecorder
    from heatmap_tpu.obs.xproc import episode_path

    # the default-spec wiring: the floor env feeds an op="lt" spec
    specs = {s.name: s for s in default_specs(
        {"HEATMAP_SLO_FORECAST_SKILL": "0.1"})}
    spec = specs["forecast_skill"]
    assert spec.op == "lt" and spec.threshold == 0.1
    assert specs["nis_band"].op == "gt"

    state = {"v": 0.5}

    def expo():
        # two horizons: the WORST one (min) must drive the lt-spec
        return ("# TYPE heatmap_quality_forecast_skill gauge\n"
                'heatmap_quality_forecast_skill'
                '{grid="h3r8",h="120"} 0.9\n'
                'heatmap_quality_forecast_skill'
                f'{{grid="h3r8",h="300"}} {state["v"]}\n')

    obs = QualityObservatory(_qcfg(), tag="s0")
    obs.note_fold(t=BASE, updates=10, inside=9, inn_n=0.0, inn_e=0.0,
                  anomalies={}, table={})
    fr = FlightRecorder(str(tmp_path / "fr"))
    fr.add_source("quality", obs.snapshot)
    chan = str(tmp_path / "chan.json")
    clk = [0.0]
    rec = TsdbRecorder(expo, tag="s0", scrape_s=1.0,
                       clock=lambda: clk[0])
    eng = SloEngine(rec, tag="s0", specs=(spec,),
                    rules=(BurnRule("r", 4.0, 20.0, 2.5),),
                    budget_frac=0.2, budget_window_s=100.0,
                    channel_path=chan, flightrec=fr)
    st = eng._state["forecast_skill"]
    for t in range(1, 100):
        clk[0] = float(t)
        rec.scrape_once()
    assert st.firing is None and st.alerts_total == 0
    state["v"] = -0.5                           # the drift
    for t in range(100, 115):
        clk[0] = float(t)
        rec.scrape_once()
    # exactly ONE correlated episode: edge-triggered alert, claimed
    # episode, healthz degraded
    assert st.firing == "r" and st.alerts_total == 1
    assert st.episode and os.path.exists(episode_path(chan))
    check = eng.healthz_checks()["slo_forecast_skill"]
    assert check["ok"] is False
    # the flight record carries the calibration-enriched quality block
    dumps = os.listdir(str(tmp_path / "fr"))
    assert len(dumps) == 1
    with open(str(tmp_path / "fr" / dumps[0])) as fh:
        dump = json.load(fh)
    assert dump["episode_id"] == st.episode
    assert dump["quality"]["nis"]["coverage"] == 0.9
    assert dump["quality"]["scorecards"]["ok"] is True
    # recovery clears it: skill back above the floor, episode released
    state["v"] = 0.5
    for t in range(115, 140):
        clk[0] = float(t)
        rec.scrape_once()
    assert st.firing is None and st.episode is None
    assert not os.path.exists(episode_path(chan))
    assert st.alerts_total == 1                 # never re-fired


# ------------------------------------------------------- fleet stitching
def _member(skill, cov, band_err, registered, scored, pending,
            expired=0):
    return {"quality": {
        "enabled": True,
        "scorecards": {"registered": registered, "scored": scored,
                       "expired_unscorable": expired,
                       "pending": pending,
                       "ok": registered == scored + expired + pending},
        "skill": skill,
        "nis": {"coverage": cov, "band_error": band_err,
                "updates": 1000, "band": [0.85, 0.995], "bias_m": 1.0},
        "anomaly_rate": {"teleport": 0.1},
        "table": {},
    }}


def test_fleet_quality_sums_and_names_worst_shard():
    from heatmap_tpu.obs.fleet import fleet_quality

    members = {
        "shard0": _member({"h3r8|120": 0.6}, 0.95, 0.0, 10, 8, 2),
        "shard1": _member({"h3r8|120": 0.4, "h3r8|300": -0.2},
                          0.70, 0.15, 6, 3, 2, expired=1),
    }
    out = fleet_quality(members)
    assert out["scorecards"] == {"registered": 16, "scored": 11,
                                 "expired_unscorable": 1, "pending": 4,
                                 "ok": True}
    assert out["nis"]["updates"] == 2000
    assert out["nis"]["coverage"] == round((950 + 700) / 2000, 4)
    assert out["anomaly_rate"]["teleport"] == 0.2
    worst = out["worst_shard"]
    assert worst["tag"] == "shard1" and worst["band_error"] == 0.15
    assert worst["min_skill"] == -0.2
    assert worst["grid"] == "h3r8" and worst["h"] == "300"
    # a member without the block contributes nothing and breaks nothing
    out = fleet_quality({"s": {"up": True}})
    assert out["scorecards"]["registered"] == 0
    assert out["worst_shard"] is None


# ------------------------------------------------------------ obs_top
def test_obs_top_renders_quality_rows():
    top = _load_tool("obs_top")
    m = {
        "heatmap_quality_forecast_skill": {
            '{grid="h3r8",h="120"}': 0.62,
            '{grid="h3r8",h="300"}': -0.31},
        "heatmap_quality_nis_coverage": {"": 0.71},
        "heatmap_quality_nis_band_error": {"": 0.14},
        "heatmap_quality_pending_scorecards": {"": 3.0},
        "heatmap_quality_anomaly_rate": {'{reason="teleport"}': 0.25,
                                         '{reason="stopped"}': 0.05},
    }
    frame = top.render_frame(m, None, 0.0, None)
    assert "quality" in frame
    assert "-0.31" in frame and "h3r8|300s" in frame   # WORST horizon
    assert "0.71" in frame and "band err 0.14" in frame
    assert "pending 3" in frame and "0.30" in frame
    # knob-off: no row at all
    assert "quality" not in top.render_frame({}, None, 0.0, None)

    fleet_text = """\
heatmap_fleet_member_up{proc="shard0",role="runtime"} 1
heatmap_fleet_member_up{proc="shard1",role="runtime"} 1
heatmap_quality_forecast_skill{proc="shard0",grid="h3r8",h="120"} 0.62
heatmap_quality_forecast_skill{proc="shard1",grid="h3r8",h="120"} -0.31
heatmap_quality_nis_coverage{proc="shard0"} 0.95
heatmap_quality_nis_coverage{proc="shard1"} 0.71
heatmap_quality_nis_band_error{proc="shard0"} 0
heatmap_quality_nis_band_error{proc="shard1"} 0.14
heatmap_quality_pending_scorecards{proc="shard0"} 1
heatmap_quality_pending_scorecards{proc="shard1"} 3
heatmap_quality_scorecards_total{proc="shard0",outcome="scored"} 9
heatmap_quality_scorecards_total{proc="shard1",outcome="scored"} 4
heatmap_quality_scorecards_total{proc="shard1",\
outcome="expired_unscorable"} 2
"""
    fm = top.parse_prom(fleet_text)
    frame = top.render_fleet_frame(fm, None, 0.0, None)
    assert "quality" in frame and "shard0" in frame
    assert "quality worst shard shard1" in frame
    assert "band err 0.140" in frame
    # quality-less members render no quality table
    up_only = top.parse_prom(
        'heatmap_fleet_member_up{proc="s",role="serve"} 1\n')
    assert "quality" not in top.render_fleet_frame(up_only, None, 0.0,
                                                   None)


# ------------------------------------------------------ bench provenance
def test_quality_stamp_knob_gated_and_counts_drift_alerts(tmp_path):
    assert quality_stamp(env={}) == {}
    assert quality_stamp(env={"HEATMAP_QUALITY": "0"}) == {}
    blk = _member({"h3r8|120": 0.6, "h3r8|300": 0.2}, 0.95, 0.0,
                  4, 4, 0)["quality"]
    for tag, alerts in (("a", 2), ("b", 1)):
        d = tmp_path / tag
        d.mkdir()
        (d / "slo-state.json").write_text(json.dumps({
            "tag": tag,
            "specs": {"forecast_skill": {"alerts_total": alerts},
                      "repl_lag": {"alerts_total": 7}}}))
    out = quality_stamp(blk, env={"HEATMAP_QUALITY": "1",
                                  "HEATMAP_TSDB_DIR": str(tmp_path)})
    assert out == {"quality": {"enabled": True, "live_skill": 0.2,
                               "nis_coverage": 0.95,
                               "drift_alerts": 3}}
    # no tsdb dir: enabled stamp with zero alert provenance
    out = quality_stamp(None, env={"HEATMAP_QUALITY": "1"})
    assert out["quality"]["drift_alerts"] == 0
    assert out["quality"]["live_skill"] is None


def _infer_art(dir_path, rnd, skill=0.5, quality=None, rc=0):
    art = {"rc": rc, "entities_per_sec": 1e6, "forecast_skill": 0.4,
           "overhead_frac": 0.05, "entities": 100000,
           "reducers": {"set": ["count", "kalman"]}}
    if quality is not None:
        art["quality"] = dict({"enabled": True, "live_skill": skill,
                               "nis_coverage": 0.95,
                               "drift_alerts": 0}, **quality)
    p = dir_path / f"BENCH_INFER_r{rnd:02d}.json"
    p.write_text(json.dumps(art))
    return p


def test_regress_quality_refusals_and_live_skill_ratchet(tmp_path,
                                                         capsys):
    m = _load_tool("check_bench_regress")
    # clean pair, small live-skill move: OK
    _infer_art(tmp_path, 1, skill=0.50, quality={})
    _infer_art(tmp_path, 2, skill=0.49, quality={})
    assert m.compare_infer(str(tmp_path), 0.05) == 0
    assert "live_skill" in capsys.readouterr().out
    # live-skill collapse: the ratchet fails the pair
    _infer_art(tmp_path, 2, skill=0.10, quality={})
    assert m.compare_infer(str(tmp_path), 0.05) == 1
    assert "live forecast-skill regression" in capsys.readouterr().err
    # a drift-alerted artifact is refused outright
    _infer_art(tmp_path, 2, skill=0.50, quality={"drift_alerts": 2})
    assert m.compare_infer(str(tmp_path), 0.05) == 1
    assert "drift alert" in capsys.readouterr().err
    # a mixed quality-knob pair is refused even when both are clean
    _infer_art(tmp_path, 2, skill=0.50)        # knob-off round
    assert m.compare_infer(str(tmp_path), 0.05) == 1
    assert "quality knob-state mismatch" in capsys.readouterr().err
    # same knob both sides, no stamps at all: pre-quality pairs ratchet
    # exactly as before (byte-compatible provenance)
    _infer_art(tmp_path, 1)
    _infer_art(tmp_path, 2)
    assert m.compare_infer(str(tmp_path), 0.05) == 0
    capsys.readouterr()
