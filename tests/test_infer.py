"""Streaming inference engine units (ISSUE 19): reducer selection,
the bounded entity table, the vmapped Kalman rounds scan, and the
engine's policy layer (anomalies, velocity fields, forecasts) — plus
the retroactive forecast scorer's pure functions.

The load-bearing invariant everywhere: per-entity observation order is
(ts, stream order), a total order invariant under ANY re-batching, so
filter state / velocity fields / forecasts are byte-identical whether
a stream arrives as one batch or many — and anomaly event sets are
exactly reproducible (publication order may differ across batch
boundaries; the tests compare sorted multisets).
"""

import importlib.util
import os

import numpy as np
import pytest

from heatmap_tpu.config import load_config
from heatmap_tpu.infer.engine import InferenceEngine
from heatmap_tpu.infer.entities import EntityTable
from heatmap_tpu.infer.kalman import M_PER_DEG, filter_rounds
from heatmap_tpu.infer.reducer import (
    CountReducer,
    build_reducers,
    parse_reducers,
)
from heatmap_tpu.stream.events import columns_from_arrays

LAT0, LNG0 = 42.36, -71.06


def _cfg(**kw):
    kw.setdefault("store", "memory")
    kw.setdefault("serve_port", 0)
    kw.setdefault("reducers", ("count", "kalman"))
    return load_config({}, **kw)


# ------------------------------------------------------------ reducers
def test_parse_reducers_normalizes_and_validates():
    assert parse_reducers("count") == ("count",)
    # canonical order + dedup: one spelling per set, however written
    assert parse_reducers("kalman,count") == ("count", "kalman")
    assert parse_reducers(" count , kalman , count ") == ("count", "kalman")
    with pytest.raises(ValueError, match="unknown reducer"):
        parse_reducers("count,sgd")
    with pytest.raises(ValueError, match="must include 'count'"):
        parse_reducers("kalman")


def test_build_reducers_composition():
    rs = build_reducers(_cfg())
    assert [r.name for r in rs] == ["count", "kalman"]
    # count alone constructs no engine — the byte-identity pin holds
    # by construction on the default path
    only = build_reducers(_cfg(reducers=("count",)))
    assert len(only) == 1 and isinstance(only[0], CountReducer)
    assert only[0].emit() == {} and only[0].snapshot() == {}


# -------------------------------------------------------- entity table
def test_entity_table_seed_lookup_ttl_lru():
    t = EntityTable(8)
    vids = np.arange(8, dtype=np.int64)
    t.seed(vids, [f"v{i}" for i in range(8)],
           np.full(8, LAT0, np.float32), np.full(8, LNG0, np.float32),
           np.arange(1000, 1008, dtype=np.int64),
           np.zeros(8, np.int16), now_ts=1008, ttl_s=900.0,
           p0_pos=625.0, p0_vel=100.0)
    assert t.occupancy == 8
    assert list(t.slots_of(vids)) == sorted(t.slots_of(vids))
    # TTL: entities silent past the ttl free their slots
    assert t.evict_ttl(now_ts=1004 + 900, ttl_s=900.0) == 4
    assert t.occupancy == 4
    assert (t.slots_of(vids[:4]) < 0).all()
    assert (t.slots_of(vids[4:]) >= 0).all()
    # LRU: a full table evicts the globally oldest last-observation
    # slots first, exactly as many as the shortfall (now_ts close
    # enough that the TTL sweep can't free anything first)
    newv = np.arange(8, 14, dtype=np.int64)
    t.seed(newv, [f"v{i}" for i in newv],
           np.full(6, LAT0, np.float32), np.full(6, LNG0, np.float32),
           np.full(6, 1500, np.int64), np.zeros(6, np.int16),
           now_ts=1500, ttl_s=900.0, p0_pos=625.0, p0_vel=100.0)
    assert t.occupancy == 8
    assert t.n_evicted_lru == 2  # v4, v5 were oldest
    assert (t.slots_of(np.array([4, 5])) < 0).all()
    assert (t.slots_of(np.array([6, 7])) >= 0).all()
    # conservation: every seed is still tracked or accounted evicted
    assert t.n_seeded == t.occupancy + t.n_evicted_ttl + t.n_evicted_lru


def test_entity_table_snapshot_restore_roundtrip():
    t = EntityTable(16)
    vids = np.arange(5, dtype=np.int64)
    t.seed(vids, [f"veh-{i}" for i in range(5)],
           np.full(5, LAT0, np.float32), np.full(5, LNG0, np.float32),
           np.arange(100, 105, dtype=np.int64), np.zeros(5, np.int16),
           now_ts=105, ttl_s=900.0, p0_pos=625.0, p0_vel=100.0)
    t.x[t.slots_of(vids)] = np.arange(20, dtype=np.float32).reshape(5, 4)
    snap = t.snapshot()
    # restore into a FRESH intern map: names are the stable key,
    # intern ids are not
    t2 = EntityTable(16)
    intern = {}
    assert t2.restore(snap, intern) == 5
    assert t2.occupancy == 5
    s2 = t2.slots_of(np.asarray([intern[f"veh-{i}"] for i in range(5)],
                                np.int64))
    assert (s2 >= 0).all()
    np.testing.assert_array_equal(
        t2.x[s2], t.x[t.slots_of(vids)])
    # capacity shrink keeps the most recently observed entities
    t3 = EntityTable(8)
    big = EntityTable(16)
    vids = np.arange(12, dtype=np.int64)
    big.seed(vids, [f"veh-{i}" for i in range(12)],
             np.full(12, LAT0, np.float32), np.full(12, LNG0, np.float32),
             np.arange(100, 112, dtype=np.int64), np.zeros(12, np.int16),
             now_ts=112, ttl_s=900.0, p0_pos=625.0, p0_vel=100.0)
    assert t3.restore(big.snapshot(), {}) == 8
    kept = {n for n in t3.names if n}
    assert kept == {f"veh-{i}" for i in range(4, 12)}


def test_entity_table_capacity_floor():
    with pytest.raises(ValueError, match=">= 8"):
        EntityTable(4)


# ------------------------------------------------------------- kalman
def _run_rounds(z, dt, valid=None, reseed=None, x=None, P=None):
    k, m = z.shape[:2]
    if valid is None:
        valid = np.ones((k, m), bool)
    if reseed is None:
        reseed = np.zeros((k, m), bool)
    if x is None:
        x = np.zeros((m, 4), np.float32)
    if P is None:
        P = np.zeros((m, 4, 4), np.float32)
        P[:, 0, 0] = P[:, 1, 1] = 625.0
        P[:, 2, 2] = P[:, 3, 3] = 100.0
    # drop the trailing innovation output: these tests pin the state/
    # gate behavior; obs.quality's calibration tests cover innovations
    return filter_rounds(x, P, z.astype(np.float32),
                         dt.astype(np.float32), valid, reseed,
                         q=0.5, r_m=25.0, gate=13.816,
                         p0_pos=625.0, p0_vel=100.0)[:5]


def test_kalman_converges_on_constant_velocity():
    vn, ve = 8.0, -3.0
    k = 24
    t = np.arange(1, k + 1, dtype=np.float64) * 5.0
    z = np.stack([vn * t, ve * t], axis=1)[:, None, :]
    dt = np.full((k, 1), 5.0)
    x, P, nis, tele, spd = _run_rounds(z, dt)
    assert not tele.any()
    assert abs(x[0, 2] - vn) < 0.5 and abs(x[0, 3] - ve) < 0.5
    # filtered speed output tracks the true speed once warm
    true_spd = float(np.hypot(vn, ve))
    assert abs(spd[-1, 0] - true_spd) < 0.5
    # covariance stays symmetric positive-diagonal (Joseph + compact
    # symmetric storage: exact by construction)
    np.testing.assert_array_equal(P[0], P[0].T)
    assert (np.diag(P[0]) > 0).all()


def test_kalman_gate_reseeds_on_teleport():
    z = np.array([[[10.0, 0.0]], [[20.0, 0.0]], [[50_000.0, 0.0]]])
    dt = np.full((3, 1), 5.0)
    x, P, nis, tele, spd = _run_rounds(z, dt)
    assert not tele[0, 0] and not tele[1, 0]
    assert tele[2, 0]
    # the gated observation does NOT update: state re-seeds at z with
    # zero velocity and the seed prior
    np.testing.assert_allclose(x[0, :2], [50_000.0, 0.0])
    np.testing.assert_allclose(x[0, 2:], [0.0, 0.0])
    assert P[0, 0, 0] == pytest.approx(625.0)
    # NIS stays visible on the teleport round — it is the score
    assert nis[2, 0] > 13.816


def test_kalman_handoff_reseed_precedence_over_gate():
    # an explicit reseed round with an impossible jump is a handoff,
    # NOT a teleport anomaly
    z = np.array([[[10.0, 0.0]], [[80_000.0, 0.0]]])
    dt = np.full((2, 1), 5.0)
    rs = np.array([[False], [True]])
    x, P, nis, tele, spd = _run_rounds(z, dt, reseed=rs)
    assert not tele.any()
    np.testing.assert_allclose(x[0, :2], [80_000.0, 0.0])
    assert nis[1, 0] == 0.0  # reseed rounds carry no score


def test_kalman_padding_and_dt_clamp_invariance():
    rng = np.random.default_rng(3)
    k, m = 5, 6
    z = rng.normal(0, 50, (k, m, 2))
    dt = rng.uniform(1, 10, (k, m))
    out_a = _run_rounds(z.copy(), dt.copy())
    # wider M (extra always-invalid entities) must not perturb the
    # original lanes: padding is masked out exactly
    z2 = np.concatenate([z, rng.normal(0, 50, (k, 3, 2))], axis=1)
    dt2 = np.concatenate([dt, rng.uniform(1, 10, (k, 3))], axis=1)
    valid2 = np.ones((k, m + 3), bool)
    valid2[:, m:] = False
    out_b = _run_rounds(z2, dt2, valid=valid2)
    np.testing.assert_array_equal(out_a[0], out_b[0][:m])        # x
    np.testing.assert_array_equal(out_a[1], out_b[1][:m])        # P
    for a, b in zip(out_a[2:], out_b[2:]):                       # K x M
        np.testing.assert_array_equal(a, b[:, :m])
    # negative dt clamps to a same-time measurement, never negative
    # time in the transition
    zc = np.array([[[5.0, 5.0]], [[6.0, 5.0]]])
    neg = _run_rounds(zc, np.array([[2.0], [-7.0]]))
    zero = _run_rounds(zc, np.array([[2.0], [0.0]]))
    np.testing.assert_array_equal(neg[0], zero[0])


# ------------------------------------------------------------- engine
def _fleet_cols(n, t0, rounds, cadence=5.0, v_ms=10.0, stop_after=None):
    """n vehicles advancing north at v_ms, one observation per round;
    vehicle i offset east so entities land in distinct cells."""
    lat, lng, spd, ts, vid = [], [], [], [], []
    for r in range(rounds):
        t = t0 + r * cadence
        for i in range(n):
            moving = stop_after is None or r < stop_after
            d = (r * cadence if moving else stop_after * cadence) * v_ms
            lat.append(LAT0 + d / M_PER_DEG)
            lng.append(LNG0 + i * 0.02)
            spd.append(v_ms * 3.6 if moving else 0.0)
            ts.append(int(t))
            vid.append(i)
    return (np.asarray(lat), np.asarray(lng), np.asarray(spd),
            np.asarray(ts, np.int64), np.asarray(vid, np.int32),
            [f"veh-{i}" for i in range(n)])


def _cols_slice(fleet, sel):
    lat, lng, spd, ts, vid, names = fleet
    return columns_from_arrays(lat[sel], lng[sel], spd[sel], ts[sel],
                               vehicle_id=vid[sel], vehicles=names)


def _anom_key(e):
    return (e["entity"], e["reason"], e["t"], e["cell"], e["score"])


def test_engine_rebatching_byte_identity():
    """One batch vs three batches vs shuffled rows: filter state,
    velocity fields, and forecasts byte-identical; anomaly multisets
    equal.  THE invariance the replay differentials build on."""
    fleet = _fleet_cols(7, 10_000, 12)
    n = len(fleet[0])
    engines = []
    for splits in ([slice(0, n)],
                   [slice(0, n // 3), slice(n // 3, 2 * n // 3),
                    slice(2 * n // 3, n)]):
        eng = InferenceEngine(_cfg())
        for s in splits:
            eng.fold_batch(_cols_slice(fleet, s))
        engines.append(eng)
    # row order WITHIN a batch must not matter either: the fold sorts
    # by (vehicle, ts, stream order)
    rng = np.random.default_rng(5)
    perm = rng.permutation(n)
    # keep per-(vehicle, ts) stream order stable: our fleet has unique
    # (vehicle, ts) pairs, so any permutation is order-safe
    eng = InferenceEngine(_cfg())
    eng.fold_batch(_cols_slice(fleet, perm))
    engines.append(eng)
    base = engines[0]
    b_slots = base.table.slots_of(np.arange(7))
    for other in engines[1:]:
        o_slots = other.table.slots_of(np.arange(7))
        np.testing.assert_array_equal(base.table.x[b_slots],
                                      other.table.x[o_slots])
        np.testing.assert_array_equal(base.table.P[b_slots],
                                      other.table.P[o_slots])
        assert base.velocity_field(8) == other.velocity_field(8)
        assert base.forecast_cells(120.0, 8) == other.forecast_cells(
            120.0, 8)
        assert (sorted(map(_anom_key, base.drain_anomalies()))
                == sorted(map(_anom_key, other.drain_anomalies())))


def test_engine_velocity_field_and_forecast_advect_north():
    eng = InferenceEngine(_cfg())
    fleet = _fleet_cols(4, 50_000, 15, v_ms=12.0)
    eng.fold_batch(_cols_slice(fleet, slice(None)))
    vf = eng.velocity_field(eng.base_res)
    assert vf, "warm entities must populate the field"
    for vx_e, vy_n, cnt in vf.values():
        # northbound fleet: vy (north) ~= 12 m/s = 43.2 km/h, vx ~ 0
        assert abs(vy_n - 43.2) < 4.0
        assert abs(vx_e) < 2.0
        assert cnt >= 1
    # the forecast advects the same state: h seconds on, the occupied
    # cells move north of today's
    now_cells = eng.forecast_cells(0.0, eng.base_res)
    fut_cells = eng.forecast_cells(600.0, eng.base_res)
    assert sum(now_cells.values()) == sum(fut_cells.values()) == 4
    assert set(fut_cells) != set(now_cells)


def test_engine_stopped_anomaly_edge_triggered():
    eng = InferenceEngine(_cfg(entity_stop_s=30.0))
    # move 10 rounds, then sit still for 20 rounds (5 s cadence)
    fleet = _fleet_cols(2, 80_000, 30, stop_after=10)
    eng.fold_batch(_cols_slice(fleet, slice(None)))
    evs = eng.drain_anomalies()
    stopped = [e for e in evs if e["reason"] == "stopped"]
    # edge-triggered: exactly one per vehicle, not one per still round
    assert sorted(e["entity"] for e in stopped) == ["veh-0", "veh-1"]
    assert all(e["speedKmh"] < 3.6 for e in stopped)


def test_engine_teleport_anomaly_and_reseed_accounting():
    eng = InferenceEngine(_cfg())
    fleet = _fleet_cols(1, 90_000, 8)
    eng.fold_batch(_cols_slice(fleet, slice(None)))
    # same vehicle, 60 km away 5 s later: an impossible innovation
    jump = columns_from_arrays(
        np.array([LAT0 + 0.55]), np.array([LNG0]), np.array([30.0]),
        np.array([90_000 + 8 * 5], np.int64),
        vehicle_id=np.array([0], np.int32), vehicles=["veh-0"])
    eng.fold_batch(jump)
    evs = eng.drain_anomalies()
    tele = [e for e in evs if e["reason"] == "teleport"]
    assert len(tele) == 1 and tele[0]["entity"] == "veh-0"
    assert tele[0]["score"] > 13.8
    assert eng.table.n_reseed_teleport == 1
    # the filter recovered AT the observed position — in the SAME
    # reference frame (frames are fixed at seed time; re-anchoring
    # would make f32 rounding depend on batch boundaries)
    s = eng.table.slots_of(np.array([0]))[0]
    pn = float(eng.table.x[s, 0])  # north offset about the seed ref
    assert abs(pn - 0.55 * M_PER_DEG) < 60.0  # f32 @ 61 km ~ few m
    np.testing.assert_array_equal(eng.table.x[s, 2:], [0.0, 0.0])


def test_engine_snapshot_restore_equals_uninterrupted():
    fleet = _fleet_cols(5, 70_000, 10)
    n = len(fleet[0])
    solid = InferenceEngine(_cfg())
    solid.fold_batch(_cols_slice(fleet, slice(0, n)))

    first = InferenceEngine(_cfg())
    first.fold_batch(_cols_slice(fleet, slice(0, n // 2)))
    snap = first.snapshot()
    resumed = InferenceEngine(_cfg())
    intern = {}
    assert resumed.restore(snap, intern) == 5
    # replay the tail with the RESUMED intern ids (names are the key)
    lat, lng, spd, ts, vid, names = fleet
    sel = slice(n // 2, n)
    re_vid = np.asarray([intern[names[v]] for v in vid[sel]], np.int32)
    resumed.fold_batch(columns_from_arrays(
        lat[sel], lng[sel], spd[sel], ts[sel],
        vehicle_id=re_vid, vehicles=list(intern)))
    a = solid.table.slots_of(np.arange(5))
    b = resumed.table.slots_of(
        np.asarray([intern[f"veh-{i}"] for i in range(5)], np.int64))
    np.testing.assert_array_equal(solid.table.x[a], resumed.table.x[b])
    np.testing.assert_array_equal(solid.table.P[a], resumed.table.P[b])
    assert solid.forecast_cells(300.0, 8) == resumed.forecast_cells(
        300.0, 8)


def test_engine_member_block_conservation():
    eng = InferenceEngine(_cfg(entity_capacity=8))
    fleet = _fleet_cols(20, 60_000, 3)  # 20 entities into 8 slots
    eng.fold_batch(_cols_slice(fleet, slice(None)))
    blk = eng.member_block()
    assert blk["capacity"] == 8 and blk["entities"] == 8
    assert (blk["seeded"] == blk["entities"] + blk["evicted_ttl"]
            + blk["evicted_lru"])
    assert blk["events_folded"] == len(fleet[0])


# ----------------------------------------------------- score_forecast
def _scorer():
    repo = os.path.abspath(os.path.join(os.path.dirname(__file__),
                                        os.pardir))
    spec = importlib.util.spec_from_file_location(
        "score_forecast", os.path.join(repo, "tools", "score_forecast.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_score_forecast_pure_functions():
    sf = _scorer()
    feats = [{"cellId": "a", "count": 3}, {"cellId": "b", "count": 1},
             {"cellId": "a", "count": 1}]
    assert sf.features_to_counts(feats) == {"a": 4.0, "b": 1.0}
    assert sf.normalize({"a": 4.0, "b": 1.0}) == {"a": 0.8, "b": 0.2}
    assert sf.normalize({}) == {}
    assert sf.mae({"a": 1.0}, {"a": 1.0}) == 0.0
    assert sf.mae({}, {}) == 0.0
    # unit-mismatch robustness: scaling every count 100x (events vs
    # entities) must not move the normalized score at all
    actual = {"a": 6.0, "b": 3.0, "c": 1.0}
    fc = {"a": 5.0, "b": 4.0, "c": 1.0}
    pers = {"a": 1.0, "b": 1.0, "c": 8.0}
    s1 = sf.score_maps(fc, pers, actual)
    s2 = sf.score_maps({k: v * 100 for k, v in fc.items()}, pers, actual)
    assert s1["skill_vs_persistence"] == s2["skill_vs_persistence"]
    assert s1["skill_vs_persistence"] > 0  # fc is closer than pers
    # a perfect forecast scores 1.0
    assert sf.score_maps(actual, pers, actual)[
        "skill_vs_persistence"] == 1.0
