"""Test harness config: run JAX on CPU with 8 virtual devices.

The multi-chip sharding path (SURVEY.md SS4(d)) is exercised without TPUs via
XLA's host-platform device-count override; these env vars must be set before
jax is imported anywhere in the test process.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(0)
