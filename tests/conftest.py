"""Test harness config: run JAX on CPU with 8 virtual devices.

The multi-chip sharding path (SURVEY.md §4(d)) is exercised without TPUs.
The environment's sitecustomize imports jax at interpreter startup with
JAX_PLATFORMS=axon, so env vars are too late here — the jax.config API is
the only reliable override (backends initialize lazily on first use).
"""

import os

# The environment pins JAX_PLATFORMS=axon (one real TPU) and its
# sitecustomize imports jax at interpreter startup, so env vars set here are
# too late — use the config API instead (backends initialize lazily on first
# use, which happens inside the tests).
os.environ["JAX_PLATFORMS"] = "cpu"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # older jaxlib: the config option doesn't exist, but the XLA flag is
    # honored at (lazy) backend init, which happens inside the tests
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")
if not hasattr(jax, "enable_x64"):
    # jax.enable_x64 graduated from jax.experimental after this
    # environment's jax; alias it so the hexgrid f64-oracle tests run
    # on both
    from jax.experimental import enable_x64 as _enable_x64

    jax.enable_x64 = _enable_x64
# persistent compile cache: the suite is dominated by CPU XLA compiles
jax.config.update("jax_compilation_cache_dir", "/tmp/jax-test-cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(0)


@pytest.fixture(autouse=True)
def _quiesce_stack_sampler():
    """Stop the process-wide stack sampler (obs.prof) after any test
    that started it — directly, via /debug/stacks, or via a
    flightrec-armed runtime.  In production it is designed to stay
    running; across a test SESSION a sampler left over from one test
    holds µs-scale frame references into every later test, which is
    exactly the cross-test coupling a hermetic suite can't have."""
    yield
    from heatmap_tpu.obs import prof

    if prof._SAMPLER is not None:
        prof._SAMPLER.stop()
