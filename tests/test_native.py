"""Native C++ decoder: differential tests against the Python oracle
(stream.events.parse_events) plus streaming-chunk semantics."""

import json

import numpy as np
import pytest

from heatmap_tpu.native import NativeDecoder
from heatmap_tpu.stream.events import parse_events

pytestmark = pytest.mark.skipif(
    not NativeDecoder.available(), reason="no C++ toolchain"
)


def events_bytes(events):
    return ("\n".join(json.dumps(e) for e in events) + "\n").encode()


def mk(n=200, seed=3):
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        out.append({
            "provider": "mbta" if i % 3 else "opensky",
            "vehicleId": f"veh-{i % 17}",
            "lat": float(rng.uniform(-90, 90)),
            "lon": float(rng.uniform(-180, 180)),
            "speedKmh": float(rng.uniform(0, 200)),
            "bearing": float(rng.uniform(0, 360)),
            "accuracyM": 5.0,
            "ts": f"2026-07-{(i % 28) + 1:02d}T12:{i % 60:02d}:30Z",
        })
    return out


def assert_matches_oracle(events):
    data = events_bytes(events)
    dec = NativeDecoder()
    got, consumed = dec.decode(data)
    want = parse_events(events)
    assert consumed == len(data)
    assert len(got) == len(want)
    assert got.n_dropped == want.n_dropped
    np.testing.assert_array_equal(got.lat_deg, want.lat_deg)
    np.testing.assert_array_equal(got.lng_deg, want.lng_deg)
    np.testing.assert_array_equal(got.speed_kmh, want.speed_kmh)
    np.testing.assert_array_equal(got.ts_s, want.ts_s)
    got_p = [got.providers[i] for i in got.provider_id]
    want_p = [want.providers[i] for i in want.provider_id]
    assert got_p == want_p
    got_v = [got.vehicles[i] for i in got.vehicle_id]
    want_v = [want.vehicles[i] for i in want.vehicle_id]
    assert got_v == want_v


def test_valid_events_match_oracle():
    assert_matches_oracle(mk())


def test_malformed_and_invalid_match_oracle():
    events = mk(20)
    bad = [
        {"provider": None, "vehicleId": "x", "lat": 1.0, "lon": 1.0,
         "ts": "2026-01-01T00:00:00Z"},
        {"provider": "p", "vehicleId": "x", "lat": 91.0, "lon": 1.0,
         "ts": "2026-01-01T00:00:00Z"},
        {"provider": "p", "vehicleId": "x", "lat": 1.0, "lon": -181.0,
         "ts": "2026-01-01T00:00:00Z"},
        {"provider": "p", "vehicleId": "x", "lat": 1.0, "lon": 1.0,
         "ts": "garbage"},
        {"provider": "p", "vehicleId": "x", "lon": 1.0,
         "ts": "2026-01-01T00:00:00Z"},  # missing lat
        {"provider": "p", "vehicleId": "x", "lat": 1.0, "lon": 1.0,
         "ts": 1.7e12},  # epoch millis out of range
        {"provider": "p", "vehicleId": "x", "lat": 1.0, "lon": 1.0,
         "ts": 1_700_000_000, "speedKmh": None},
        {"provider": "p", "vehicleId": "Nächster Halt",
         "lat": 1.0, "lon": 1.0, "ts": 1_700_000_000},
        {"provider": "p", "vehicleId": "y", "lat": 2.0, "lon": 2.0,
         "ts": 1_700_000_000, "extra": {"nested": [1, 2, {"a": "b"}]}},
    ]
    assert_matches_oracle(events + bad + mk(20, seed=9))


def test_garbage_lines():
    data = b'not json\n{"broken\n\n' + events_bytes(mk(3))
    dec = NativeDecoder()
    got, consumed = dec.decode(data)
    assert len(got) == 3
    assert got.n_dropped == 2
    assert consumed == len(data)


def test_iso_offsets_and_fractions():
    events = [
        {"provider": "p", "vehicleId": "a", "lat": 1.0, "lon": 1.0,
         "ts": "2026-07-29T12:00:00+02:00"},
        {"provider": "p", "vehicleId": "b", "lat": 1.0, "lon": 1.0,
         "ts": "2026-07-29T12:00:00.500Z"},
        {"provider": "p", "vehicleId": "c", "lat": 1.0, "lon": 1.0,
         "ts": "2026-07-29 12:00:00-05:00"},
    ]
    assert_matches_oracle(events)


def test_partial_trailing_line():
    events = mk(5)
    data = events_bytes(events)
    cut = data[:-20]  # truncate mid-record, no trailing newline
    dec = NativeDecoder()
    got, consumed = dec.decode(cut)
    assert len(got) == 4
    # unconsumed tail starts at the last (partial) line boundary
    assert cut[consumed:].startswith(b'{"provider"')


def test_intern_stability_across_batches():
    dec = NativeDecoder()
    a, _ = dec.decode(events_bytes(mk(10)))
    b, _ = dec.decode(events_bytes(mk(10)))
    assert a.providers is b.providers or a.providers == b.providers
    pa = [a.providers[i] for i in a.provider_id]
    pb = [b.providers[i] for i in b.provider_id]
    assert pa == pb


def test_final_flushes_unterminated_tail():
    events = mk(3)
    data = events_bytes(events)[:-1]  # complete last record, no newline
    dec = NativeDecoder()
    got, consumed = dec.decode(data, final=True)
    assert len(got) == 3
    assert consumed == len(data)


def test_nul_and_lone_surrogate_names_match_oracle():
    # a NUL escape inside a name must not truncate; a lone surrogate must
    # round-trip the same way Python's json preserves it
    lines = (
        '{"provider": "p", "vehicleId": "a\\u0000x", "lat": 1.0, "lon": 1.0, "ts": 1700000000}\n'
        '{"provider": "p", "vehicleId": "a\\u0000y", "lat": 1.0, "lon": 1.0, "ts": 1700000000}\n'
        '{"provider": "p", "vehicleId": "\\ud800", "lat": 1.0, "lon": 1.0, "ts": 1700000000}\n'
    ).encode()
    dec = NativeDecoder()
    got, consumed = dec.decode(lines)
    assert consumed == len(lines)
    assert len(got) == 3
    names = [got.vehicles[i] for i in got.vehicle_id]
    assert names == ["a\x00x", "a\x00y", "\ud800"]


def test_string_encoded_numerics_match_oracle():
    """The Python path coerces "42.36" via float(); the C++ path must
    accept the same events or acceptance becomes toolchain-dependent."""
    events = [
        {"provider": "p", "vehicleId": "s1", "lat": "42.36", "lon": "-71.06",
         "speedKmh": " 30.5 ", "ts": 1_700_000_000},
        {"provider": "p", "vehicleId": "s2", "lat": "91.5", "lon": "0",
         "ts": 1_700_000_000},      # out of range even as a string
        {"provider": "p", "vehicleId": "s3", "lat": "not-a-number",
         "lon": "1.0", "ts": 1_700_000_000},   # -> dropped both paths
        {"provider": "p", "vehicleId": "s4", "lat": "0x20", "lon": "1.0",
         "ts": 1_700_000_000},   # C99 hex float: float() rejects -> drop
        {"provider": "p", "vehicleId": "s5", "lat": "4_2.0", "lon": "1.0",
         "ts": 1_700_000_000},   # Python underscore literal: accepted, 42.0
        {"provider": "p", "vehicleId": "s6", "lat": "inf", "lon": "1.0",
         "ts": 1_700_000_000},   # parses but non-finite -> drop
        {"provider": "p", "vehicleId": "s7", "lat": "1.0", "lon": "1.0",
         "speedKmh": "0x20", "ts": 1_700_000_000},  # bad speed -> 0.0, kept
        {"provider": "p", "vehicleId": "s8", "lat": "-1e1", "lon": "+.5",
         "ts": 1_700_000_000},   # sign/exponent/bare-fraction forms
    ]
    assert_matches_oracle(events)


def test_decode_lines_tolerates_embedded_newlines():
    """A pretty-printed (multi-line) JSON value must decode whole, not
    split into dropped fragments."""
    from heatmap_tpu.native import decode_lines

    pretty = (b'{\n  "provider": "mbta",\n  "vehicleId": "v1",\n'
              b'  "lat": 42.3,\n  "lon": -71.05,\n  "ts": 1700000000\n}')
    compact = (b'{"provider": "mbta", "vehicleId": "v2", "lat": 42.4, '
               b'"lon": -71.0, "ts": 1700000001}')
    cols = decode_lines(NativeDecoder(), [pretty, compact])
    assert len(cols) == 2
    assert [cols.vehicles[i] for i in cols.vehicle_id] == ["v1", "v2"]


def test_cap_limits_output():
    dec = NativeDecoder()
    data = events_bytes(mk(10))
    got, consumed = dec.decode(data, max_events=4)
    assert len(got) == 4
    assert consumed < len(data)
    # the rest decodes from the consumed offset
    got2, consumed2 = dec.decode(data[consumed:])
    assert len(got2) == 6

def test_numeric_identity_matches_oracle():
    """Unquoted numeric provider/vehicleId (an unwrapped MBTA label,
    producers/mbta.py, ref :68) is str()-coerced by parse_events — the
    C++ decoder must accept it identically, not drop the event as a null
    identity (regression)."""
    evs = mk(3)
    evs[0]["vehicleId"] = 1711
    evs[1]["provider"] = 42
    evs[2]["vehicleId"] = 0
    assert_matches_oracle(evs)
