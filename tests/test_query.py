"""Query tier: materialized tile view, pyramid rollup, delta protocol.

The acceptance property of the subsystem is REPLAY EQUIVALENCE:
applying /api/tiles/delta responses from since=0 must reproduce the
exact /api/tiles/latest feature set (sorted byte-compare), across
window advance, staleAt eviction, and multi-grid configs.  The tests
here drive it three ways: view-level with a fake clock (eviction),
HTTP-level against a live runtime (the acceptance check proper), and
serve-only against a store written out-of-process-style.
"""

import datetime as dt
import json
import tempfile
import time
import urllib.error
import urllib.request

import pytest

from heatmap_tpu import hexgrid
from heatmap_tpu.config import load_config
from heatmap_tpu.query import Pyramid, StoreViewRefresher, TileMatView
from heatmap_tpu.query.pyramid import cell_to_parent
from heatmap_tpu.sink import MemoryStore
from heatmap_tpu.sink.base import TileDoc, UTC


# ---------------------------------------------------------------- parent
def test_cell_to_parent_structure():
    """Parent = same index with the res field lowered and freed digits
    invalidated — cross-checked against the host packer."""
    import math

    from heatmap_tpu.hexgrid import host

    for lat, lng in [(42.36, -71.05), (-33.9, 151.2), (64.1, -21.9),
                     (0.01, 0.01), (37.77, -122.42)]:
        child = host.latlng_to_cell_int(
            math.radians(lat), math.radians(lng), 9)
        base, digits, res = host.unpack(child)
        assert res == 9
        for pres in (8, 6, 3, 0):
            parent = cell_to_parent(child, pres)
            assert parent == host.pack(base, digits[:pres], pres)
            assert host.get_resolution(parent) == pres
            assert host.get_base_cell(parent) == base
    with pytest.raises(ValueError):
        cell_to_parent(child, 10)  # finer than the cell itself


def _doc(cell, ws, count, speed, lat=42.3, lon=-71.05, grid="h3r8",
         ttl_minutes=45, extra=None):
    return TileDoc("bos", 8, cell, ws, ws + dt.timedelta(minutes=5),
                   count=count, avg_speed_kmh=speed, avg_lat=lat,
                   avg_lon=lon, ttl_minutes=ttl_minutes, extra=extra,
                   grid=grid)


def _cells(n, res=8, lat0=42.30):
    out = []
    for i in range(n * 3):
        c = hexgrid.latlng_to_cell(lat0 + i * 7e-3, -71.05, res)
        if c not in out:
            out.append(c)
        if len(out) == n:
            break
    assert len(out) == n
    return out


# --------------------------------------------------------------- pyramid
def test_pyramid_incremental_matches_recompute():
    ws_dt = dt.datetime(2026, 8, 3, 10, 0, tzinfo=UTC)
    ws = int(ws_dt.timestamp())
    cells = _cells(6)
    docs1 = [_doc(c, ws_dt, count=i + 1, speed=10.0 * (i + 1))
             for i, c in enumerate(cells)]
    # incremental: apply v1, then update half the cells to v2
    pyr = Pyramid(8, levels=3)
    for d in docs1:
        pyr.apply(ws, int(d["cellId"], 16), None, d)
    docs2 = list(docs1)
    for i in (0, 2, 4):
        new = dict(docs1[i])
        new["count"] = docs1[i]["count"] + 10
        new["avgSpeedKmh"] = 99.0
        pyr.apply(ws, int(new["cellId"], 16), docs1[i], new)
        docs2[i] = new
    # recompute from scratch over the FINAL docs
    fresh = Pyramid(8, levels=3)
    for d in docs2:
        fresh.apply(ws, int(d["cellId"], 16), None, d)
    for res in (7, 6, 5):
        got = {d["cellId"]: d for d in pyr.docs(res, ws, None, None)}
        want = {d["cellId"]: d for d in fresh.docs(res, ws, None, None)}
        assert set(got) == set(want)
        for cid in want:
            assert got[cid]["count"] == want[cid]["count"]
            assert got[cid]["avgSpeedKmh"] == pytest.approx(
                want[cid]["avgSpeedKmh"])
        # and against brute force: counts sum, speeds count-weighted
        brute: dict = {}
        for d in docs2:
            p = hexgrid.h3_to_string(
                cell_to_parent(int(d["cellId"], 16), res))
            c, s = brute.get(p, (0, 0.0))
            brute[p] = (c + d["count"], s + d["count"] * d["avgSpeedKmh"])
        assert {k: v[0] for k, v in brute.items()} == {
            k: v["count"] for k, v in want.items()}
        for k, (c, s) in brute.items():
            assert want[k]["avgSpeedKmh"] == pytest.approx(s / c)


def test_pyramid_zero_count_entry_drops():
    ws_dt = dt.datetime(2026, 8, 3, 10, 0, tzinfo=UTC)
    ws = int(ws_dt.timestamp())
    (cell,) = _cells(1)
    d1 = _doc(cell, ws_dt, count=5, speed=20.0)
    pyr = Pyramid(8, levels=1)
    pyr.apply(ws, int(cell, 16), None, d1)
    assert len(pyr.docs(7, ws, None, None)) == 1
    d0 = dict(d1)
    d0["count"] = 0
    pyr.apply(ws, int(cell, 16), d1, d0)
    assert pyr.docs(7, ws, None, None) == []


# --------------------------------------------------- delta protocol (view)
def _applier():
    """The documented delta client: full replaces, delta upserts."""
    state = {"cells": {}, "since": 0}

    def apply(view, grid):
        d = view.delta(grid, state["since"])
        if d["mode"] == "full":
            state["cells"] = {}
        for doc in d["docs"]:
            state["cells"][doc["cellId"]] = doc
        state["since"] = d["seq"]
        return state["cells"]

    return state, apply


def _latest_map(view, grid):
    _, docs = view.latest_docs(grid)
    return {d["cellId"]: d for d in docs}


def test_delta_replay_window_advance_and_log_horizon():
    view = TileMatView(delta_log=4)
    # relative windowStart: a fixed date would cross its staleAt horizon
    # mid-suite and evict under the view's real clock (time bomb)
    ws1 = dt.datetime.now(UTC).replace(microsecond=0) - \
        dt.timedelta(minutes=6)
    cells = _cells(8)
    state, apply = _applier()

    view.apply_docs([_doc(cells[0], ws1, 1, 10.0)])
    assert apply(view, "h3r8") == _latest_map(view, "h3r8")
    d = view.delta("h3r8", state["since"])
    assert d["mode"] == "delta" and d["docs"] == []  # idle -> empty delta

    # same-window updates flow as deltas
    view.apply_docs([_doc(cells[1], ws1, 2, 20.0)])
    d = view.delta("h3r8", state["since"])
    assert d["mode"] == "delta" and len(d["docs"]) == 1
    assert apply(view, "h3r8") == _latest_map(view, "h3r8")

    # a NEW window forces a full resync (the client's baseline window died)
    ws2 = ws1 + dt.timedelta(minutes=5)
    view.apply_docs([_doc(cells[2], ws2, 3, 30.0)])
    d = view.delta("h3r8", state["since"])
    assert d["mode"] == "full"
    assert apply(view, "h3r8") == _latest_map(view, "h3r8")
    assert set(apply(view, "h3r8")) == {cells[2]}

    # blow past the 4-deep changelog in one gap -> full resync
    for i, c in enumerate(cells[3:]):
        view.apply_docs([_doc(c, ws2, 4 + i, 40.0)])
    d = view.delta("h3r8", state["since"])
    assert d["mode"] == "full"
    assert apply(view, "h3r8") == _latest_map(view, "h3r8")
    # a client from the FUTURE (restarted server) resyncs too
    assert view.delta("h3r8", 10**9)["mode"] == "full"


def test_delta_replay_across_eviction_fake_clock():
    """staleAt eviction mirrors the store TTL; evicting the latest
    window forces delta clients through full resync, and the applied
    set keeps matching the latest render byte-for-byte."""
    clock = {"t": 1_900_000_000.0}
    view = TileMatView(now_fn=lambda: clock["t"])
    base = dt.datetime.fromtimestamp(clock["t"], UTC)
    ws1 = base - dt.timedelta(minutes=10)
    ws2 = base - dt.timedelta(minutes=5)
    cells = _cells(4)
    state, apply = _applier()
    # ttl 6min: ws1 stale at ws1+5min+6min = base+1min; ws2 at base+6min
    view.apply_docs([_doc(cells[0], ws1, 1, 10.0, ttl_minutes=6),
                     _doc(cells[1], ws1, 2, 20.0, ttl_minutes=6)])
    assert set(apply(view, "h3r8")) == {cells[0], cells[1]}
    view.apply_docs([_doc(cells[2], ws2, 3, 30.0, ttl_minutes=6)])
    assert set(apply(view, "h3r8")) == {cells[2]}  # window advanced
    # ws1 quietly evicts (not latest): nothing visible changes
    clock["t"] += 120
    seq_before = view.seq
    assert view.delta("h3r8", state["since"])["docs"] == []
    assert apply(view, "h3r8") == _latest_map(view, "h3r8")
    # ws2 evicts too -> the latest window is GONE: full resync to empty
    clock["t"] += 360
    d = view.delta("h3r8", state["since"])
    assert d["mode"] == "full" and d["docs"] == []
    assert apply(view, "h3r8") == {} == _latest_map(view, "h3r8")
    assert view.seq > seq_before  # eviction of the latest is a change
    # and the ETag moved with it
    assert view.etag("h3r8").split(".")[-1].rstrip('"') == str(view.seq)


def test_view_apply_is_idempotent_per_doc():
    view = TileMatView()
    ws = dt.datetime.now(UTC).replace(microsecond=0) - \
        dt.timedelta(minutes=2)
    (cell,) = _cells(1)
    doc = _doc(cell, ws, 5, 25.0)
    assert view.apply_docs([doc]) == 1
    s = view.seq
    assert view.apply_docs([dict(doc)]) == 0  # unchanged doc: no-op
    assert view.seq == s


# ------------------------------------------------------- runtime parity
def _mini_runtime(tmpdir, events, **cfg_over):
    from heatmap_tpu.stream import MicroBatchRuntime
    from heatmap_tpu.stream.source import MemorySource

    cfg = load_config({}, batch_size=16, state_capacity_log2=8,
                      speed_hist_bins=4, store="memory", serve_port=0,
                      checkpoint_dir=tempfile.mkdtemp(dir=tmpdir),
                      **cfg_over)
    src = MemorySource(events)
    st = MemoryStore()
    rt = MicroBatchRuntime(cfg, src, st, checkpoint_every=0)
    return cfg, src, st, rt


def _evs(n, t0, lat0=42.0):
    return [{"provider": "p", "vehicleId": f"v{i}", "lat": lat0 + i * 1e-3,
             "lon": -71.0, "speedKmh": 10.0 + i, "ts": t0 + i}
            for i in range(n)]


def test_runtime_view_matches_store(tmp_path):
    """The writer-fed view holds exactly the docs a Store read-back
    returns — the invariant that lets /latest stop touching the Store."""
    t0 = int(time.time()) - 30
    cfg, src, st, rt = _mini_runtime(str(tmp_path), _evs(48, t0))
    src.finish()
    rt.run()
    assert rt.matview is not None and not rt.matview.poisoned
    grid = cfg.default_grid()
    ws = st.latest_window_start(grid)
    store_docs = {d["cellId"]: d for d in st.tiles_in_window(ws, grid)}
    ws_dt, view_docs = rt.matview.latest_docs(grid)
    assert ws_dt == ws
    assert {d["cellId"]: d for d in view_docs} == store_docs


def test_query_view_disabled_by_env(tmp_path):
    t0 = int(time.time()) - 30
    cfg, src, st, rt = _mini_runtime(str(tmp_path), _evs(8, t0),
                                     query_view=False)
    src.finish()
    rt.run()
    assert rt.matview is None


# ---------------------------------------------- HTTP replay equivalence
def _get(url, hdrs=None):
    req = urllib.request.Request(url)
    for k, v in (hdrs or {}).items():
        req.add_header(k, v)
    with urllib.request.urlopen(req, timeout=10) as r:
        return r.status, dict(r.headers), r.read()


def _sorted_features(raw_fc: bytes) -> list:
    fc = json.loads(raw_fc)
    feats = fc["features"]
    return sorted((json.dumps(f, sort_keys=True) for f in feats))


def test_http_delta_replay_equivalence_multigrid(tmp_path):
    """ACCEPTANCE: applying /api/tiles/delta responses from since=0
    reproduces the exact /api/tiles/latest feature set (sorted
    byte-compare) for every grid of a multi-grid config, across window
    advance, polled WHILE the runtime streams."""
    from heatmap_tpu.serve import start_background

    t0 = int(time.time()) - 900
    cfg, src, st, rt = _mini_runtime(
        str(tmp_path), [], resolutions=(7, 8), windows_minutes=(5,))
    httpd, _t, port = start_background(st, cfg, runtime=rt, port=0)
    base = f"http://127.0.0.1:{port}"
    grids = ("h3r7", "h3r8")
    client = {g: {"cells": {}, "since": 0} for g in grids}

    def poll(g):
        _, _, b = _get(base + f"/api/tiles/delta?since={client[g]['since']}"
                       f"&grid={g}")
        d = json.loads(b)
        if d["mode"] == "full":
            client[g]["cells"] = {}
        for f in d["features"]:
            client[g]["cells"][f["properties"]["cellId"]] = f
        client[g]["since"] = d["seq"]

    try:
        # three segments, the last crossing into a NEW 5-min window
        for seg, (n, ts) in enumerate([(32, t0), (32, t0 + 40),
                                       (32, t0 + 600)]):
            src.push(_evs(n, ts, lat0=42.0 + seg * 0.01))
            while rt.step_once():
                pass
            rt.flush_pending()
            rt.writer.drain()
            for g in grids:
                poll(g)
        # runtime idle: the client state must now equal the full render
        for g in grids:
            poll(g)  # drain any tail
            _, _, full = _get(base + f"/api/tiles/latest?grid={g}")
            want = _sorted_features(full)
            got = sorted(json.dumps(f, sort_keys=True)
                         for f in client[g]["cells"].values())
            assert got == want, f"delta replay diverged for {g}"
            assert len(want) > 0
    finally:
        httpd.shutdown()
        httpd.server_close()
        rt.close()


def test_serve_only_rebuild_and_delta(tmp_path):
    """Serve-only mode: no runtime in-process — the view rebuilds from
    a pre-populated Store by version polling, serves ETag 304s, and
    flows subsequent store writes out as deltas."""
    from heatmap_tpu.serve import start_background

    st = MemoryStore()
    now = dt.datetime.now(UTC).replace(microsecond=0)
    ws = now - dt.timedelta(minutes=2)
    cells = _cells(6)
    st.upsert_tiles([_doc(c, ws, i + 1, 10.0 + i)
                     for i, c in enumerate(cells[:4])])
    cfg = load_config({}, serve_port=0)
    httpd, _t, port = start_background(st, cfg, port=0)  # runtime=None
    base = f"http://127.0.0.1:{port}"
    try:
        stn, h, b = _get(base + "/api/tiles/latest")
        assert len(json.loads(b)["features"]) == 4
        etag = h["ETag"]
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(base + "/api/tiles/latest", {"If-None-Match": etag})
        assert ei.value.code == 304
        _, _, b = _get(base + "/api/tiles/delta?since=0")
        d = json.loads(b)
        assert d["mode"] == "full" and len(d["features"]) == 4
        since = d["seq"]
        # an out-of-band store write (version bump) flows as a DELTA
        st.upsert_tiles([_doc(cells[4], ws, 9, 50.0)])
        _, _, b = _get(base + f"/api/tiles/delta?since={since}")
        d2 = json.loads(b)
        assert d2["mode"] == "delta"
        assert [f["properties"]["cellId"] for f in d2["features"]] == \
            [cells[4]]
        # the ETag moved; the old one re-renders, the new one 304s
        _, h2, _ = _get(base + "/api/tiles/latest",
                        {"If-None-Match": etag})
        assert h2["ETag"] != etag
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_refresher_idle_store_keeps_seq_stable():
    st = MemoryStore()
    now = dt.datetime.now(UTC).replace(microsecond=0)
    ws = now - dt.timedelta(minutes=2)
    st.upsert_tiles([_doc(c, ws, i + 1, 10.0)
                     for i, c in enumerate(_cells(3))])
    view = TileMatView()
    ref = StoreViewRefresher(st, view, poll_s=0.0)  # rebuild every call
    ref.refresh("h3r8")
    s = view.seq
    for _ in range(5):
        ref.refresh("h3r8")
    assert view.seq == s  # unchanged store -> unchanged seq -> stable ETags
    assert view.etag("h3r8") == view.etag("h3r8")


def test_runtime_view_seeded_from_durable_store(tmp_path):
    """A streaming process restarting against a durable store must not
    serve an empty map: the serve layer seeds the writer-fed view from
    a one-time store scan on first access (r6 review finding).  Runtime
    construction itself stays read-only — the seed happens at the serve
    layer, not at boot."""
    from heatmap_tpu.serve import start_background
    from heatmap_tpu.stream import MicroBatchRuntime
    from heatmap_tpu.stream.source import MemorySource

    st = MemoryStore()
    now = dt.datetime.now(UTC).replace(microsecond=0)
    ws = now - dt.timedelta(minutes=2)
    cells = _cells(3)
    st.upsert_tiles([_doc(c, ws, i + 1, 20.0)
                     for i, c in enumerate(cells)])
    cfg = load_config({}, batch_size=16, state_capacity_log2=8,
                      speed_hist_bins=4, store="memory", serve_port=0,
                      checkpoint_dir=tempfile.mkdtemp(dir=str(tmp_path)))
    src = MemorySource([])
    src.finish()
    rt = MicroBatchRuntime(cfg, src, st, checkpoint_every=0)
    assert rt.matview.seq == 0  # boot did NOT scan the store
    httpd, _t, port = start_background(st, cfg, runtime=rt, port=0)
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/api/tiles/latest",
                timeout=10) as r:
            fc = json.loads(r.read())
        assert {f["properties"]["cellId"] for f in fc["features"]} == \
            set(cells)
        ws_dt, docs = rt.matview.latest_docs("h3r8")
        assert ws_dt == ws
    finally:
        httpd.shutdown()
        httpd.server_close()
        rt.close()


def test_etag_carries_boot_nonce():
    """Seq counters restart at 0 per process; the ETag must still never
    repeat across restarts for different content (r6 review finding)."""
    a, b = TileMatView(), TileMatView()
    ws = dt.datetime.now(UTC).replace(microsecond=0)
    doc = _doc(_cells(1)[0], ws, 1, 10.0)
    a.apply_docs([doc])
    b.apply_docs([doc])
    assert a.etag("h3r8") != b.etag("h3r8")  # same state, different boot


def test_refresher_transient_store_error_does_not_poison():
    class FlakyStore(MemoryStore):
        def __init__(self):
            super().__init__()
            self.fail = False

        def latest_window_start(self, grid=None):
            if self.fail:
                raise IOError("injected store outage")
            return super().latest_window_start(grid)

    st = FlakyStore()
    now = dt.datetime.now(UTC).replace(microsecond=0)
    ws = now - dt.timedelta(minutes=2)
    cells = _cells(2)
    st.upsert_tiles([_doc(cells[0], ws, 1, 10.0)])
    view = TileMatView()
    ref = StoreViewRefresher(st, view, poll_s=0.0)
    ref.refresh("h3r8")
    assert len(view.latest_docs("h3r8")[1]) == 1
    st.fail = True
    ref.refresh("h3r8")  # outage: serves the last materialized state
    assert not view.poisoned
    assert len(view.latest_docs("h3r8")[1]) == 1
    st.fail = False
    st.upsert_tiles([_doc(cells[1], ws, 2, 20.0)])
    ref.refresh("h3r8")  # recovered: next poll converges
    assert len(view.latest_docs("h3r8")[1]) == 2


def test_late_window_writes_do_not_flap_etag():
    """Late events landing in a NON-latest window change nothing a
    client can see: the ETag must hold (no spurious re-renders for the
    whole polling fleet) and deltas stay empty (r6 review finding)."""
    view = TileMatView()
    now = dt.datetime.now(UTC).replace(microsecond=0)
    ws_old = now - dt.timedelta(minutes=10)
    ws_new = now - dt.timedelta(minutes=5)
    cells = _cells(3)
    view.apply_docs([_doc(cells[0], ws_old, 1, 10.0)])
    view.apply_docs([_doc(cells[1], ws_new, 2, 20.0)])
    etag = view.etag("h3r8")
    since = view.seq
    # a late straggler updates the OLD window only
    view.apply_docs([_doc(cells[2], ws_old, 3, 30.0)])
    assert view.etag("h3r8") == etag
    assert not view.changed_since("h3r8", since)
    d = view.delta("h3r8", since)
    assert d["mode"] == "delta" and d["docs"] == []
    # a latest-window write DOES move everything
    view.apply_docs([_doc(cells[2], ws_new, 4, 40.0)])
    assert view.etag("h3r8") != etag
    assert view.changed_since("h3r8", since)


# ---------------------------------------------------- bbox edge cases
# (ISSUE 13 satellite: only the happy path was pinned; the continuous-
# query geometry compilation leans on exactly these boundaries)
def test_topk_bbox_zero_area_and_outside_region():
    """A zero-area bbox through the topk centroid filter matches only
    a centroid EXACTLY on the point (practically nothing — the
    point-geofence shape lives in query.geom, which compiles the
    containing CELL instead); a bbox entirely outside the folded
    region matches nothing at base res and at every pyramid rollup
    res."""
    ws_dt = dt.datetime.now(UTC).replace(second=0, microsecond=0)
    cells = _cells(4)
    view = TileMatView(pyramid_levels=2)
    view.apply_docs([_doc(c, ws_dt, count=i + 1, speed=10.0,
                          lat=42.30 + i * 0.01, lon=-71.05)
                     for i, c in enumerate(cells)])
    # zero-area bbox off any tile centroid: nothing
    assert view.topk("h3r8", 10, bbox=(-71.049, 42.3012,
                                       -71.049, 42.3012)) == []
    # zero-area bbox ON a tile's (count-weighted) centroid: that tile
    got = view.topk("h3r8", 10, bbox=(-71.05, 42.30, -71.05, 42.30))
    assert [d["cellId"] for d in got] == [cells[0]]
    # bbox entirely outside the folded region: empty at base res...
    far = (10.0, 50.0, 10.5, 50.5)
    assert view.topk("h3r8", 10, bbox=far) == []
    # ...and at the pyramid rollup resolutions (same centroid filter
    # over synthesized parent docs)
    for res in (7, 6):
        assert view.topk("h3r8", 10, res=res, bbox=far) == []
        assert view.topk("h3r8", 10, res=res) != []


def test_serve_bbox_parser_rejects_antimeridian_wrap():
    """The ONE-SHOT ``bbox=`` parser stays strict: a wrapped
    (min_lon > max_lon) box is a 400, not a silent empty result —
    standing queries accept the wrap via query.geom.compile_bbox
    (pinned in tests/test_cq.py), which splits it into the two
    straddling boxes."""
    from heatmap_tpu.query import geom
    from heatmap_tpu.serve.api import _parse_bbox

    bbox, err = _parse_bbox({"bbox": "179.9,-17.0,-179.9,-16.9"})
    assert bbox is None and "min exceeds max" in err
    # the standing-query path accepts the same shape
    cs = geom.compile_bbox([179.9, -17.0, -179.9, -16.9], 8)
    assert cs.size() > 0


def test_pyramid_parent_math_on_antimeridian_cells():
    """cell_to_parent is pure bit surgery — cells straddling ±180 roll
    up exactly like any other (the geom compiler's index keys depend
    on it)."""
    import math

    from heatmap_tpu.hexgrid import host

    for lon in (179.999, -179.999, 180.0, -180.0):
        child = host.latlng_to_cell_int(math.radians(-16.99),
                                        math.radians(lon), 9)
        base, digits, res = host.unpack(child)
        for pres in (8, 7, 5):
            parent = cell_to_parent(child, pres)
            assert parent == host.pack(base, digits[:pres], pres)
    # and ±180 name the same meridian, so the same parents
    a = host.latlng_to_cell_int(math.radians(-16.99),
                                math.radians(180.0), 9)
    b = host.latlng_to_cell_int(math.radians(-16.99),
                                math.radians(-180.0), 9)
    assert cell_to_parent(a, 7) == cell_to_parent(b, 7)
