"""SLO error-budget burn-rate engine (obs/slo.py, ISSUE 18).

The headline test scripts a synthetic clock through a known error rate
and pins the alert to the EXACT predicted tick — scrape 1 s, budget
5 %×... no: budget_frac 0.2 over a 100 s window (budget 20 s), one rule
(short 4 s / long 20 s / 2.5× burn), badness starting at t=100:

- long window at t: ticks in (t-20, t]; frac crosses 2.5×·0.2 = 0.5
  when 10 of 20 ticks are bad → first true at t=109, NOT at t=108
  (9/20 = 0.45 → 2.25×);
- short window is already saturated (4/4 bad → 5×) by then;
- recovery from t=110: short window frac falls to 1/4 (1.25×) at
  t=112 → resolve, exactly two ticks after the last good-burn tick.

The budget ledger at fire time is hand-computable: 10 bad ticks × 1 s
= 10 s consumed of 20 s → remaining_frac 0.5.
"""

import json
import os

from heatmap_tpu.obs.slo import (BurnRule, SloEngine, SloSpec,
                                 default_rules, default_specs,
                                 slo_stamp)
from heatmap_tpu.obs.tsdb import TsdbRecorder
from heatmap_tpu.obs.xproc import episode_path


def _gauge_engine(tmp_path=None, channel=None):
    """Recorder+engine over one synthetic gauge, synthetic clock."""
    state = {"v": 0.0}

    def expo():
        return ("# TYPE heatmap_repl_lag_seconds gauge\n"
                f"heatmap_repl_lag_seconds {state['v']}\n")

    clk = [0.0]
    rec = TsdbRecorder(expo, tag="m0",
                      dir_path=str(tmp_path) if tmp_path else None,
                      scrape_s=1.0, flush_s=1e9, clock=lambda: clk[0])
    eng = SloEngine(
        rec, tag="m0",
        specs=(SloSpec("repl_lag", "gauge",
                       "heatmap_repl_lag_seconds", 10.0),),
        rules=(BurnRule("r", 4.0, 20.0, 2.5),),
        budget_frac=0.2, budget_window_s=100.0,
        channel_path=channel)
    return rec, eng, state, clk


def _tick(rec, state, clk, t, v):
    clk[0] = float(t)
    state["v"] = float(v)
    rec.scrape_once()


def test_burn_rate_fires_at_predicted_tick_exactly(tmp_path):
    chan = str(tmp_path / "chan.json")
    rec, eng, state, clk = _gauge_engine(channel=chan)
    st = eng._state["repl_lag"]
    for t in range(1, 100):
        _tick(rec, state, clk, t, 0.0)          # good
    assert st.firing is None and st.alerts_total == 0

    for t in range(100, 109):                   # bad t=100..108
        _tick(rec, state, clk, t, 99.0)
        assert st.firing is None, f"fired EARLY at t={t}"
    assert st.alerts_total == 0

    _tick(rec, state, clk, 109, 99.0)           # the predicted tick
    assert st.firing == "r" and st.severity == "page"
    assert st.alerts_total == 1
    # the ledger matches the hand computation
    assert eng.budget("repl_lag") == {
        "window_s": 100.0, "budget_frac": 0.2, "budget_s": 20.0,
        "consumed_s": 10.0, "remaining_s": 10.0,
        "remaining_frac": 0.5}
    # the durable event carries the burn multiples and the episode
    ev = list(rec._events)[-1]
    assert ev["kind"] == "slo_alert" and ev["slo"] == "repl_lag"
    assert ev["burn_short"] == 5.0 and ev["burn_long"] == 2.5
    assert ev["budget"]["consumed_s"] == 10.0
    # a firing alert claims ONE fleet episode (obs.xproc)
    assert st.episode and st.episode_claimed
    assert ev["episode"] == st.episode
    assert os.path.exists(episode_path(chan))

    # recovery: good from t=110; both windows stay tripped through
    # t=111 (10/20 long = 2.5x), resolve exactly at t=112
    for t in (110, 111):
        _tick(rec, state, clk, t, 0.0)
        assert st.firing == "r", f"resolved EARLY at t={t}"
    _tick(rec, state, clk, 112, 0.0)
    assert st.firing is None and st.episode is None
    ev = list(rec._events)[-1]
    assert ev["kind"] == "slo_resolve" and ev["episode"]
    # the claimed episode was released on resolve
    assert not os.path.exists(episode_path(chan))
    # alert count is edge-triggered, not re-fired per bad tick
    assert st.alerts_total == 1


def test_blip_warns_burn_degrades():
    rec, eng, state, clk = _gauge_engine()
    for t in range(1, 60):
        _tick(rec, state, clk, t, 0.0)
    _tick(rec, state, clk, 60, 99.0)            # ONE bad tick
    check = eng.healthz_checks()["slo_repl_lag"]
    assert check["ok"] is True                  # a blip never degrades
    assert check.get("warn") is True
    assert "momentary blip" in check["detail"]

    for t in range(61, 75):                     # sustained burn
        _tick(rec, state, clk, t, 99.0)
    check = eng.healthz_checks()["slo_repl_lag"]
    assert check["ok"] is False
    assert "error budget burning fast" in check["detail"]
    assert "rule=r" in check["detail"]


def test_counter_spec_reset_aware():
    state = {"v": 5.0}

    def expo():
        return ("# TYPE heatmap_audit_digest_mismatch_total counter\n"
                "heatmap_audit_digest_mismatch_total "
                f"{state['v']}\n")

    clk = [0.0]
    rec = TsdbRecorder(expo, tag="m0", scrape_s=1.0,
                      clock=lambda: clk[0])
    eng = SloEngine(
        rec, tag="m0",
        specs=(SloSpec("mism", "counter",
                       "heatmap_audit_digest_mismatch_total", 0.0),),
        rules=(BurnRule("r", 4.0, 20.0, 1e9),),
        budget_frac=0.2, budget_window_s=100.0)
    st = eng._state["mism"]
    for t, v in [(1, 5.0), (2, 7.0), (3, 1.0), (4, 1.0)]:
        clk[0] = float(t)
        state["v"] = v
        rec.scrape_once()
    # first tick seeds the baseline (good); +2 bad; reset -> the new
    # total (1) IS the increase (bad); flat -> good
    assert list(st.samples) == [(1.0, 0), (2.0, 1), (3.0, 1), (4.0, 0)]


def test_quantile_spec_no_traffic_is_no_sample():
    state = {"n": 5.0}

    def expo():
        return (
            "# TYPE heatmap_event_age_seconds histogram\n"
            f'heatmap_event_age_seconds_bucket{{le="0.1"}} {state["n"]}\n'
            f'heatmap_event_age_seconds_bucket{{le="+Inf"}} {state["n"]}\n')

    clk = [1.0]
    rec = TsdbRecorder(expo, tag="m0", scrape_s=1.0,
                      clock=lambda: clk[0])
    eng = SloEngine(
        rec, tag="m0",
        specs=(SloSpec("fresh", "quantile", "heatmap_event_age_seconds",
                       10.0, q=0.5),),
        rules=(BurnRule("r", 4.0, 20.0, 1e9),),
        budget_frac=0.2, budget_window_s=100.0)
    st = eng._state["fresh"]
    rec.scrape_once()                           # 5 obs since baseline 0
    assert len(st.samples) == 1 and st.last_bad is False
    clk[0] = 2.0
    rec.scrape_once()                           # same totals: no traffic
    assert len(st.samples) == 1                 # no data ≠ good or bad


def test_default_specs_and_rules_shape():
    specs = {s.name: s for s in default_specs({})}
    assert specs["freshness_p50"].threshold == 10.0
    assert specs["delivered_p99"].q == 0.99
    assert specs["audit_mismatch"].kind == "counter"
    over = default_specs({"HEATMAP_SLO_REPL_LAG_S": "3"})
    assert {s.name: s for s in over}["repl_lag"].threshold == 3.0
    # canonical 30d window pairs scale linearly; tiny windows clamp to
    # two scrape ticks so a rule can always distinguish blip from burn
    fast, slow = default_rules(30.0 * 86400.0, 5.0)
    assert (fast.short_s, fast.long_s, fast.burn) == (300.0, 3600.0,
                                                      14.4)
    assert slow.severity == "ticket"
    fast, _slow = default_rules(20.0, 0.1)
    assert fast.short_s == 0.2 and fast.long_s == 0.2


def test_state_persisted_for_cross_process_stamp(tmp_path):
    rec, eng, state, clk = _gauge_engine(tmp_path=tmp_path)
    for t in range(1, 30):
        _tick(rec, state, clk, t, 99.0)
    p = tmp_path / "m0" / "slo-state.json"
    st = json.loads(p.read_text())
    assert st["tag"] == "m0"
    assert st["alerts_fired_total"] == 1
    assert st["worst_burn"] >= 2.5
    assert st["specs"]["repl_lag"]["firing"] == "r"
    assert st["specs"]["repl_lag"]["consumed_s"] > 0


def test_slo_stamp_aggregates_members(tmp_path):
    for tag, alerts, burn, frac in (("a", 2, 14.5, 0.8),
                                    ("b", 0, 1.2, 0.1)):
        mdir = tmp_path / tag
        mdir.mkdir()
        (mdir / "slo-state.json").write_text(json.dumps({
            "tag": tag, "alerts_fired_total": alerts,
            "worst_burn": burn, "budget_consumed_frac": frac,
            "specs": {}}))
    out = slo_stamp(dir_path=str(tmp_path), env={"HEATMAP_TSDB": "1"})
    assert out == {"slo": {"enabled": True, "alerts_fired": 2,
                           "worst_burn": 14.5,
                           "budget_consumed_frac": 0.8, "members": 2}}
    # knob-off: NO stamp at all — artifacts stay byte-compatible with
    # pre-tsdb rounds
    assert slo_stamp(dir_path=str(tmp_path), env={}) == {}
    assert slo_stamp(dir_path=str(tmp_path),
                     env={"HEATMAP_TSDB": "0"}) == {}


# ------------------------- bench refusal provenance (satellite, tools)
def _load_regress():
    import importlib.util

    repo = os.path.abspath(os.path.join(os.path.dirname(__file__),
                                        os.pardir))
    spec = importlib.util.spec_from_file_location(
        "check_bench_regress",
        os.path.join(repo, "tools", "check_bench_regress.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _art(dir_path, rnd, value=1_000_000.0, slo=None):
    tail = ("noise\n"
            + json.dumps({"metric": "GPS events/sec aggregated",
                          "value": value, "unit": "events/sec"}))
    art = {"n": rnd, "rc": 0, "tail": tail}
    if slo is not None:
        art["slo"] = slo
    p = dir_path / f"BENCH_r{rnd:02d}.json"
    p.write_text(json.dumps(art))
    return p


def test_regress_refuses_alert_firing_artifact(tmp_path, capsys):
    m = _load_regress()
    p = _art(tmp_path, 1, slo={"enabled": True, "alerts_fired": 2,
                               "worst_burn": 14.5,
                               "budget_consumed_frac": 0.9,
                               "members": 1})
    assert m.slo_refused(str(p), "candidate") is True
    err = capsys.readouterr().err
    assert "burn-rate alert" in err and "14.5x" in err
    clean = _art(tmp_path, 2, slo={"enabled": True, "alerts_fired": 0,
                                   "worst_burn": 0.4,
                                   "budget_consumed_frac": 0.0,
                                   "members": 1})
    assert m.slo_refused(str(clean), "candidate") is False
    unstamped = _art(tmp_path, 3)
    assert m.slo_refused(str(unstamped), "candidate") is False


def test_regress_refuses_mixed_knob_pair(tmp_path, capsys):
    m = _load_regress()
    on = _art(tmp_path, 1, slo={"enabled": True, "alerts_fired": 0,
                                "worst_burn": 0.0,
                                "budget_consumed_frac": 0.0,
                                "members": 1})
    off = _art(tmp_path, 2)
    assert m.slo_mixed_refused(str(on), str(off), "prev", "new") is True
    assert "knob-state mismatch" in capsys.readouterr().err
    on2 = _art(tmp_path, 3, slo={"enabled": True, "alerts_fired": 0,
                                 "worst_burn": 0.1,
                                 "budget_consumed_frac": 0.0,
                                 "members": 1})
    assert m.slo_mixed_refused(str(on), str(on2), "prev", "new") is False
    assert m.slo_mixed_refused(str(off), str(off), "prev",
                               "new") is False


def test_regress_main_gates_on_slo_provenance(tmp_path, capsys):
    m = _load_regress()
    clean = {"enabled": True, "alerts_fired": 0, "worst_burn": 0.2,
             "budget_consumed_frac": 0.01, "members": 1}
    _art(tmp_path, 1, 1_000_000.0, slo=clean)
    _art(tmp_path, 2, 990_000.0, slo=clean)
    assert m.main(["--dir", str(tmp_path)]) == 0
    capsys.readouterr()
    # a burn-firing newest round is refused end to end
    _art(tmp_path, 3, 1_500_000.0,
         slo=dict(clean, alerts_fired=1, worst_burn=20.0))
    assert m.main(["--dir", str(tmp_path)]) == 1
    assert "burn-rate alert" in capsys.readouterr().err
    # a mixed-knob newest pair is refused even when both are clean
    _art(tmp_path, 3, 1_000_000.0)  # overwrite: knob-off round
    assert m.main(["--dir", str(tmp_path)]) == 1
    assert "knob-state mismatch" in capsys.readouterr().err
