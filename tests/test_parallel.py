"""Sharded aggregation on the 8-virtual-device CPU mesh (SURVEY.md §4(d)).

The union of all shard states must equal the single-device dict oracle, and
keys must be disjoint across shards (the all_to_all routing contract).
"""

import numpy as np
import pytest

import jax

from heatmap_tpu.engine import AggParams
from heatmap_tpu.parallel import ShardedAggregator, make_mesh
from tests.test_engine import DictAgg, make_batch
from heatmap_tpu.engine.step import snap_and_window

PARAMS = AggParams(res=8, window_s=300, emit_capacity=1024)


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) >= 8, "conftest must provide 8 virtual devices"
    return make_mesh(8)


def shard_states_as_dict(agg: ShardedAggregator):
    """Pull global state to host; return {key: [count, ABSOLUTE sums...]}
    (reconstructed in f64 from the residual sums + per-group anchors,
    engine.state.TileState), plus the per-shard key sets for
    disjointness checks."""
    hi = np.asarray(agg.state.key_hi)
    lo = np.asarray(agg.state.key_lo)
    ws = np.asarray(agg.state.key_ws)
    cnt = np.asarray(agg.state.count)
    rsp = np.asarray(agg.state.sum_speed, dtype=np.float64)
    rsp2 = np.asarray(agg.state.sum_speed2, dtype=np.float64)
    rla = np.asarray(agg.state.sum_lat, dtype=np.float64)
    rlo = np.asarray(agg.state.sum_lon, dtype=np.float64)
    a_s = np.asarray(agg.state.anchor_speed, dtype=np.float64)
    a_la = np.asarray(agg.state.anchor_lat, dtype=np.float64)
    a_lo = np.asarray(agg.state.anchor_lon, dtype=np.float64)
    live = hi != np.uint32(0xFFFFFFFF)
    out, per_shard = {}, []
    C = agg.capacity_per_shard
    for s in range(agg.n_shards):
        keys = set()
        for i in np.nonzero(live[s * C:(s + 1) * C])[0] + s * C:
            k = (int(hi[i]), int(lo[i]), int(ws[i]))
            keys.add(k)
            c = int(cnt[i])
            out[k] = [c, a_s[i] * c + rsp[i],
                      rsp2[i] + 2.0 * a_s[i] * rsp[i] + c * a_s[i] ** 2,
                      a_la[i] * c + rla[i], a_lo[i] * c + rlo[i]]
        per_shard.append(keys)
    return out, per_shard


def test_sharded_matches_oracle(mesh, rng):
    agg = ShardedAggregator(mesh, PARAMS, capacity_per_shard=1024,
                            batch_size=1024)
    oracle = DictAgg(PARAMS)
    for b in range(3):
        lat, lng, speed, ts, valid = make_batch(rng, 1024, t0=1_700_000_000 + b * 120)
        emit, stats = agg.step(lat, lng, speed, ts, valid, -2**31)
        hi, lo, ws = snap_and_window(lat, lng, ts, valid, PARAMS)
        oracle.feed(np.asarray(hi), np.asarray(lo), np.asarray(ws), speed,
                    np.degrees(lat.astype(np.float64)),
                    np.degrees(lng.astype(np.float64)), valid, -2**31)
        assert int(stats.bucket_dropped) == 0
        assert int(stats.state_overflow) == 0
        assert int(stats.n_valid) == 1024

    got, per_shard = shard_states_as_dict(agg)
    assert set(got) == set(oracle.groups)
    for k, g in got.items():
        w = oracle.groups[k]
        assert g[0] == w[0], (k, g, w)
        np.testing.assert_allclose(g[1:], w[1:], rtol=2e-5, atol=1e-3)
    # shard disjointness: each key on exactly one shard
    all_keys = [k for s in per_shard for k in s]
    assert len(all_keys) == len(set(all_keys))
    assert int(stats.n_active) == len(got)


def test_sharded_emit_covers_touched(mesh, rng):
    agg = ShardedAggregator(mesh, PARAMS, capacity_per_shard=1024,
                            batch_size=1024)
    lat, lng, speed, ts, valid = make_batch(rng, 1024)
    emit, stats = agg.step(lat, lng, speed, ts, valid, -2**31)
    ehi = np.asarray(emit.key_hi)
    evalid = np.asarray(emit.valid)
    emitted = {
        (int(ehi[i]), int(np.asarray(emit.key_lo)[i]),
         int(np.asarray(emit.key_ws)[i]))
        for i in np.nonzero(evalid)[0]
    }
    got, _ = shard_states_as_dict(agg)
    assert emitted == set(got)
    assert int(np.asarray(emit.n_emitted).sum()) == len(emitted)
    assert not np.asarray(emit.overflowed).any()


def test_invalid_rows_do_not_steal_lanes(mesh, rng):
    # 50% invalid rows: with per-lane capacity sized for valid traffic only,
    # invalid events must not consume exchange capacity (review finding r1)
    agg = ShardedAggregator(mesh, PARAMS, capacity_per_shard=1024,
                            batch_size=1024, bucket_factor=1.5)
    lat, lng, speed, ts, valid = make_batch(rng, 1024, nan_frac=0.5)
    emit, stats = agg.step(lat, lng, speed, ts, valid, -2**31)
    assert int(stats.bucket_dropped) == 0
    assert int(stats.n_valid) == valid.sum()


def test_late_events_dropped_before_exchange(mesh, rng):
    # a fully-late batch must not drop on-time events via lane pressure
    t0 = 1_700_000_000
    agg = ShardedAggregator(mesh, PARAMS, capacity_per_shard=1024,
                            batch_size=1024, bucket_factor=1.5)
    lat, lng, speed, ts, valid = make_batch(rng, 1024, t0=t0 - 50_000)
    lat2, lng2, speed2, ts2, _ = make_batch(rng, 1024, t0=t0)
    # half late, half on-time, interleaved so every batch shard sees both
    m = np.arange(1024) % 2 == 0
    lat[m], lng[m], speed[m], ts[m] = lat2[m], lng2[m], speed2[m], ts2[m]
    emit, stats = agg.step(lat, lng, speed, ts, valid, t0 - 1000)
    assert int(stats.n_late) == 512
    assert int(stats.n_valid) == 512
    assert int(stats.bucket_dropped) == 0


def test_watermark_eviction_sharded(mesh, rng):
    agg = ShardedAggregator(mesh, PARAMS, capacity_per_shard=1024,
                            batch_size=1024)
    t0 = 1_700_000_000
    lat, lng, speed, ts, valid = make_batch(rng, 1024, t0=t0)
    agg.step(lat, lng, speed, ts, valid, -2**31)
    # advance watermark past everything
    _, stats = agg.step(lat, lng, speed, ts,
                        np.zeros_like(valid), t0 + 10_000)
    assert int(stats.n_active) == 0
    assert int(stats.n_evicted) > 0


def test_step_packed_matches_step(mesh, rng):
    """The packed single-pull pathway must decode to exactly what the
    pytree path reports: same emitted groups, same stats."""
    from heatmap_tpu.parallel import multihost
    from heatmap_tpu.parallel.sharded import unpack_emit_shards

    agg_a = ShardedAggregator(mesh, PARAMS, capacity_per_shard=1024,
                              batch_size=1024)
    agg_b = ShardedAggregator(mesh, PARAMS, capacity_per_shard=1024,
                              batch_size=1024)
    for b in range(2):
        lat, lng, speed, ts, valid = make_batch(
            rng, 1024, t0=1_700_000_000 + b * 120, nan_frac=0.2)
        emit, stats = agg_a.step(lat, lng, speed, ts, valid, -2**31)
        packed = agg_b.step_packed(lat, lng, speed, ts, valid, -2**31)
        rows = multihost.addressable_rows(packed)
        e, pstats = unpack_emit_shards(rows, PARAMS.emit_capacity)

        want = agg_a.emit_to_host(emit)
        def as_dict(d):
            idx = np.nonzero(d["valid"])[0]
            return {
                (int(d["key_hi"][i]), int(d["key_lo"][i]),
                 int(d["key_ws"][i])):
                (int(d["count"][i]), round(float(d["sum_speed"][i]), 3))
                for i in idx
            }
        assert as_dict(e) == as_dict(want)
        assert e["n_emitted"] == int(np.asarray(emit.n_emitted).sum())
        for f in ("n_valid", "n_late", "n_evicted", "n_active",
                  "state_overflow", "batch_max_ts", "bucket_dropped"):
            assert getattr(pstats, f) == int(np.asarray(getattr(stats, f))), f


def test_sharded_grow_preserves_state(mesh, rng):
    """grow() must preserve every live group (per-shard sorted prefix,
    EMPTY-padded tails) and keep subsequent folds identical to an oracle
    that never grew."""
    agg = ShardedAggregator(mesh, PARAMS, capacity_per_shard=64,
                            batch_size=1024)
    oracle = DictAgg(PARAMS)

    def feed(b, n):
        lat, lng, speed, ts, valid = make_batch(rng, 1024,
                                                t0=1_700_000_000 + b * 120)
        valid[n:] = False  # small first fill, full batches after the grow
        emit, stats = agg.step(lat, lng, speed, ts, valid, -2**31)
        hi, lo, ws = snap_and_window(lat, lng, ts, valid, PARAMS)
        oracle.feed(np.asarray(hi), np.asarray(lo), np.asarray(ws), speed,
                    np.degrees(lat.astype(np.float64)),
                    np.degrees(lng.astype(np.float64)), valid, -2**31)
        assert int(stats.state_overflow) == 0
        return stats

    feed(0, 200)  # <= 200 groups over 8x64 slots: no overflow
    before, _ = shard_states_as_dict(agg)
    agg.grow(256)
    assert agg.capacity_per_shard == 256
    after, per_shard = shard_states_as_dict(agg)
    assert after == before  # nothing lost or moved across shards
    feed(1, 1024)  # retraced step on the grown shapes, full batch
    got, _ = shard_states_as_dict(agg)
    assert set(got) == set(oracle.groups)
    for k, g in got.items():
        w = oracle.groups[k]
        assert g[0] == w[0], (k, g, w)


def test_step_packed_prekeys_matches_in_program_snap(mesh, rng):
    """Host-precomputed cell keys (HEATMAP_H3_IMPL=native's sharded
    integration) fed through step_packed(prekeys=...) must produce
    byte-identical packed emits to the in-program snap.  Feeding the
    XLA snap's own keys as prekeys isolates the plumbing: same keys in,
    so any difference is a routing/masking bug."""
    from heatmap_tpu.hexgrid.device import latlng_to_cell_vec
    from heatmap_tpu.parallel import multihost

    agg_a = ShardedAggregator(mesh, PARAMS, capacity_per_shard=1024,
                              batch_size=1024)
    agg_b = ShardedAggregator(mesh, PARAMS, capacity_per_shard=1024,
                              batch_size=1024)
    for b in range(2):
        lat, lng, speed, ts, valid = make_batch(
            rng, 1024, t0=1_700_000_000 + b * 120, nan_frac=0.2)
        hi, lo = latlng_to_cell_vec(lat, lng, PARAMS.res)
        pre = {PARAMS.res: (np.asarray(hi), np.asarray(lo))}
        p_a = agg_a.step_packed(lat, lng, speed, ts, valid, -2**31)
        p_b = agg_b.step_packed(lat, lng, speed, ts, valid, -2**31,
                                prekeys=pre)
        np.testing.assert_array_equal(
            multihost.addressable_rows(p_a),
            multihost.addressable_rows(p_b), err_msg=f"batch {b}")
    with pytest.raises(ValueError):
        agg_b.step_packed(lat, lng, speed, ts, valid, -2**31,
                          prekeys={7: pre[PARAMS.res]})
