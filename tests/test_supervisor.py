"""Supervisor tests: crash restart, stall (heartbeat) detection, restart
budget, platform failover, and the runtime-side heartbeat beacon
(SURVEY.md §5.3 — failure detection / elastic recovery, which the
reference delegates to Spark's restart-from-checkpoint model).

The children are tiny inline python scripts (no device, no jax) so each
failure mode is deterministic and fast; the beacon itself is separately
pinned against the real MicroBatchRuntime in test_runtime_heartbeat.
"""

import os
import subprocess
import sys
import time

import pytest

from heatmap_tpu.stream.supervisor import (FleetSupervisor, RestartPolicy,
                                           Supervisor)

FAST = dict(backoff_s=0.05, backoff_max_s=0.1, term_grace_s=1.0,
            window_s=60.0)


def _child(body: str) -> list[str]:
    return [sys.executable, "-c", body]


# a child that appends one line per launch so tests can count restarts,
# then acts per-launch: fail until the Nth run, then succeed
COUNTING = """
import os, sys, time
log = os.environ["LAUNCH_LOG"]
with open(log, "a") as fh:
    fh.write("launch\\n")
n = sum(1 for _ in open(log))
sys.exit(0 if n >= {succeed_on} else 1)
"""


def test_restarts_until_clean_exit(tmp_path):
    log = tmp_path / "launches"
    sup = Supervisor(
        _child(COUNTING.format(succeed_on=3)),
        RestartPolicy(max_restarts=5, **FAST),
        env={**os.environ, "LAUNCH_LOG": str(log)},
        heartbeat_path=str(tmp_path / "hb"), poll_s=0.02)
    assert sup.run() == 0
    assert sum(1 for _ in open(log)) == 3
    assert sup.restarts == 2


def test_restart_budget_exhausts(tmp_path):
    log = tmp_path / "launches"
    sup = Supervisor(
        _child(COUNTING.format(succeed_on=99)),
        RestartPolicy(max_restarts=2, **FAST),
        env={**os.environ, "LAUNCH_LOG": str(log)},
        heartbeat_path=str(tmp_path / "hb"), poll_s=0.02)
    assert sup.run() == 1          # the child's failing exit code
    # budget = max_restarts failures in window → 3 launches total
    assert sum(1 for _ in open(log)) == 3


def test_stall_detected_and_killed(tmp_path):
    """A child that starts its beacon then wedges (sleeps forever, like a
    device op whose tunnel died) must be killed and restarted; the
    second launch exits 0 immediately."""
    log = tmp_path / "launches"
    body = """
import os, sys, time
log = os.environ["LAUNCH_LOG"]
with open(log, "a") as fh:
    fh.write("launch\\n")
n = sum(1 for _ in open(log))
if n == 1:
    hb = os.environ["HEATMAP_HEARTBEAT_FILE"]
    open(hb, "w").write(str(time.time()))
    time.sleep(3600)   # wedged: beacon never updates again
sys.exit(0)
"""
    sup = Supervisor(
        _child(body),
        RestartPolicy(max_restarts=5, stall_timeout_s=8.0, **FAST),
        env={**os.environ, "LAUNCH_LOG": str(log)},
        heartbeat_path=str(tmp_path / "hb"), poll_s=0.02)
    t0 = time.monotonic()
    assert sup.run() == 0
    assert time.monotonic() - t0 < 120  # killed the sleeper, didn't wait it out
    # exactly one stall-kill-restart on an idle box; a loaded box may
    # false-stall a starting child, which just restarts again — every
    # path still ends in the clean exit asserted above
    assert sum(1 for _ in open(log)) >= 2


def test_stall_covers_wedged_startup(tmp_path):
    """A child that never writes a beacon at all (wedged inside backend
    init) is still stalled — age counts from child start."""
    log = tmp_path / "launches"
    body = """
import os, sys, time
log = os.environ["LAUNCH_LOG"]
with open(log, "a") as fh:
    fh.write("launch\\n")
if sum(1 for _ in open(log)) == 1:
    time.sleep(3600)
sys.exit(0)
"""
    sup = Supervisor(
        _child(body),
        RestartPolicy(max_restarts=5, stall_timeout_s=8.0,
                      startup_grace_s=8.0, **FAST),
        env={**os.environ, "LAUNCH_LOG": str(log)},
        heartbeat_path=str(tmp_path / "hb"), poll_s=0.02)
    assert sup.run() == 0
    assert sum(1 for _ in open(log)) >= 2


def test_failover_sets_platform(tmp_path):
    """After failover_after consecutive failures the child env gains
    HEATMAP_PLATFORM=<failover_platform>; the child proves it by
    succeeding only once it sees the override."""
    log = tmp_path / "launches"
    body = """
import os, sys
with open(os.environ["LAUNCH_LOG"], "a") as fh:
    fh.write(os.environ.get("HEATMAP_PLATFORM", "-") + "\\n")
sys.exit(0 if os.environ.get("HEATMAP_PLATFORM") == "cpu" else 1)
"""
    sup = Supervisor(
        _child(body),
        RestartPolicy(max_restarts=5, failover_after=2, **FAST),
        env={**{k: v for k, v in os.environ.items()
                if k != "HEATMAP_PLATFORM"}, "LAUNCH_LOG": str(log)},
        heartbeat_path=str(tmp_path / "hb"), poll_s=0.02)
    assert sup.run() == 0
    launches = open(log).read().split()
    assert launches == ["-", "-", "cpu"]
    assert sup.failed_over


def test_startup_grace_outlasts_stall_timeout(tmp_path):
    """A child that takes longer than stall_timeout_s before its first
    beacon (first-step compile) must NOT be killed while within
    startup_grace_s."""
    log = tmp_path / "launches"
    body = """
import os, sys, time
with open(os.environ["LAUNCH_LOG"], "a") as fh:
    fh.write("launch\\n")
time.sleep(2.0)   # "compiling": no beacon yet
sys.exit(0)
"""
    sup = Supervisor(
        _child(body),
        RestartPolicy(max_restarts=2, stall_timeout_s=0.2,
                      startup_grace_s=60.0, **FAST),
        env={**os.environ, "LAUNCH_LOG": str(log)},
        heartbeat_path=str(tmp_path / "hb"), poll_s=0.02)
    assert sup.run() == 0
    assert sum(1 for _ in open(log)) == 1


def test_healthy_run_resets_failover_streak(tmp_path):
    """Failures separated by healthy-for-a-window runs never trip
    failover_after (one blip a day must not degrade to CPU forever)."""
    log = tmp_path / "launches"
    body = """
import os, sys, time
with open(os.environ["LAUNCH_LOG"], "a") as fh:
    fh.write(os.environ.get("HEATMAP_PLATFORM", "-") + "\\n")
n = sum(1 for _ in open(os.environ["LAUNCH_LOG"]))
time.sleep(1.0)   # healthy past the (tiny) budget window
sys.exit(0 if n >= 3 else 1)
"""
    sup = Supervisor(
        _child(body),
        RestartPolicy(max_restarts=10, window_s=0.3, failover_after=2,
                      backoff_s=0.05, backoff_max_s=0.1, term_grace_s=1.0),
        env={**{k: v for k, v in os.environ.items()
                if k != "HEATMAP_PLATFORM"}, "LAUNCH_LOG": str(log)},
        heartbeat_path=str(tmp_path / "hb"), poll_s=0.02)
    assert sup.run() == 0
    assert not sup.failed_over
    assert open(log).read().split() == ["-", "-", "-"]


def test_wedged_child_still_trips_failover(tmp_path):
    """A child that only ever wedges (no beacon, killed by the startup
    grace) must NOT count as healthy — its streak accumulates and
    failover trips.  (The stall-detection wait itself is not health.)"""
    log = tmp_path / "launches"
    body = """
import os, sys, time
with open(os.environ["LAUNCH_LOG"], "a") as fh:
    fh.write(os.environ.get("HEATMAP_PLATFORM", "-") + "\\n")
if os.environ.get("HEATMAP_PLATFORM") == "cpu":
    sys.exit(0)
time.sleep(3600)   # wedged before any beacon
"""
    sup = Supervisor(
        _child(body),
        RestartPolicy(max_restarts=10, stall_timeout_s=2.0,
                      startup_grace_s=2.0, window_s=1.0,
                      failover_after=2, backoff_s=0.05,
                      backoff_max_s=0.1, term_grace_s=1.0),
        env={**{k: v for k, v in os.environ.items()
                if k != "HEATMAP_PLATFORM"}, "LAUNCH_LOG": str(log)},
        heartbeat_path=str(tmp_path / "hb"), poll_s=0.02)
    assert sup.run() == 0
    assert sup.failed_over
    assert open(log).read().split()[-1] == "cpu"


def test_separate_incidents_mint_fresh_episode_ids(tmp_path):
    """A child failure AFTER a full healthy window is a separate
    incident: the supervisor closes its previous episode broadcast
    before claiming, so the new incident gets a fresh id — joined
    stale, every surviving watchdog would skip it as already-dumped
    and the second incident would leave no correlated dump set."""
    import threading

    from heatmap_tpu.obs.xproc import read_episode

    log = tmp_path / "launches"
    chan = str(tmp_path / "chan")
    body = """
import os, sys, time
with open(os.environ["LAUNCH_LOG"], "a") as fh:
    fh.write("launch\\n")
n = sum(1 for _ in open(os.environ["LAUNCH_LOG"]))
if n >= 3:
    sys.exit(0)
time.sleep(0.5)   # healthy past the (tiny) budget window, then fail
sys.exit(1)
"""
    sup = Supervisor(
        _child(body),
        RestartPolicy(max_restarts=10, window_s=0.3, backoff_s=0.05,
                      backoff_max_s=0.1, term_grace_s=1.0),
        env={**os.environ, "LAUNCH_LOG": str(log)},
        heartbeat_path=str(tmp_path / "hb"), poll_s=0.02,
        channel_path=chan)
    rcs: list = []
    t = threading.Thread(target=lambda: rcs.append(sup.run()), daemon=True)
    t.start()
    deadline = time.monotonic() + 30
    first = None
    while time.monotonic() < deadline and first is None:
        first = read_episode(chan).get("episode_id")
        time.sleep(0.01)
    assert first, "first failure never broadcast an episode"
    t.join(timeout=30)
    assert rcs == [0]
    # the second failure's broadcast survives the run: fresh id, ours
    final = read_episode(chan)
    assert final.get("origin") == "supervisor"
    assert final["episode_id"] != first, \
        "second incident joined the stale episode id"


def test_policy_from_env():
    env = {"HEATMAP_SUPERVISE_MAX_RESTARTS": "9",
           "HEATMAP_SUPERVISE_STALL_TIMEOUT_S": "7.5",
           "HEATMAP_SUPERVISE_FAILOVER_AFTER": "2"}
    env["HEATMAP_SUPERVISE_STARTUP_GRACE_S"] = "11"
    p = RestartPolicy.from_env(env)
    assert p.max_restarts == 9
    assert p.stall_timeout_s == 7.5
    assert p.startup_grace_s == 11
    assert p.failover_after == 2
    assert p.failover_platform == "cpu"
    d = RestartPolicy.from_env({})
    assert d == RestartPolicy()


def test_runtime_heartbeat(tmp_path, monkeypatch):
    """The real MicroBatchRuntime writes the beacon from its step loop
    when HEATMAP_HEARTBEAT_FILE is set."""
    from heatmap_tpu.config import load_config
    from heatmap_tpu.sink import MemoryStore
    from heatmap_tpu.stream import MemorySource, MicroBatchRuntime

    hb = tmp_path / "hb"
    monkeypatch.setenv("HEATMAP_HEARTBEAT_FILE", str(hb))
    cfg = load_config({}, batch_size=64, state_capacity_log2=10,
                      speed_hist_bins=8, store="memory",
                      checkpoint_dir=str(tmp_path / "ckpt"))
    t0 = int(time.time()) - 600
    evs = [{"provider": "t", "vehicleId": f"v{i}", "lat": 42.0 + i * 1e-3,
            "lon": -71.0, "speedKmh": 10.0, "bearing": 0.0,
            "accuracyM": 1.0, "ts": t0 + i} for i in range(64)]
    src = MemorySource(evs)
    src.finish()
    rt = MicroBatchRuntime(cfg, src, MemoryStore())
    rt.run()
    content = open(hb).read()
    assert content.startswith(tuple("0123456789"))
    assert "epoch=" in content


def test_sigterm_during_backoff_exits_promptly(tmp_path):
    """A REAL SIGTERM delivered while the supervisor sleeps in a long
    restart backoff must stop it within ~poll_s.  stop() runs inside the
    signal handler on the sleeping main thread, so it must be
    async-signal-safe: the round-4 Event-based stop could self-deadlock
    there (Event.set() needs the Condition lock the interrupted wait
    holds); the plain-bool flag + sliced _wait cannot."""
    import signal

    repo = os.path.abspath(os.path.join(os.path.dirname(__file__),
                                        os.pardir))
    prog = (
        "import sys; sys.path.insert(0, %r); "
        "from heatmap_tpu.stream.supervisor import supervise_cli; "
        "sys.exit(supervise_cli([sys.executable, '-c', "
        "'raise SystemExit(3)']))" % repo)
    env = {**os.environ, "PYTHONPATH": "",  # skip slow interpreter hooks
           "HEATMAP_SUPERVISE_BACKOFF_S": "60",
           "HEATMAP_SUPERVISE_BACKOFF_MAX_S": "60",
           "HEATMAP_SUPERVISE_MAX_RESTARTS": "9"}
    p = subprocess.Popen([sys.executable, "-c", prog], env=env)
    try:
        time.sleep(3.0)  # child exits code 3 fast -> 60s backoff begins
        assert p.poll() is None, "supervisor ended before the signal"
        t0 = time.monotonic()
        p.send_signal(signal.SIGTERM)
        rc = p.wait(timeout=10)
        assert time.monotonic() - t0 < 5.0
        assert rc == 0  # stop() during backoff is a clean stop
    finally:
        if p.poll() is None:
            p.kill()


def test_watchdog_vouches_for_in_flight_step_up_to_grace(tmp_path,
                                                         monkeypatch):
    """The in-flight beacon watchdog keeps the beacon fresh while a step
    is dispatching (so a slow mid-run recompile outlives
    stall_timeout_s), but stops vouching once HEATMAP_DISPATCH_GRACE_S
    lapses — a truly wedged device op must still go quiet and trip the
    supervisor."""
    from heatmap_tpu.config import load_config
    from heatmap_tpu.sink import MemoryStore
    from heatmap_tpu.stream import MemorySource, MicroBatchRuntime

    hb = tmp_path / "hb"
    monkeypatch.setenv("HEATMAP_HEARTBEAT_FILE", str(hb))
    monkeypatch.setenv("HEATMAP_DISPATCH_GRACE_S", "2.5")
    cfg = load_config({}, batch_size=64, state_capacity_log2=10,
                      speed_hist_bins=8, store="memory",
                      checkpoint_dir=str(tmp_path / "ckpt"))
    t0 = int(time.time()) - 600
    src = MemorySource([{"provider": "t", "vehicleId": "v0", "lat": 42.0,
                         "lon": -71.0, "speedKmh": 10.0, "bearing": 0.0,
                         "accuracyM": 1.0, "ts": t0}])
    rt = MicroBatchRuntime(cfg, src, MemoryStore())
    rt.step_once()
    rt._touch_heartbeat()  # first beacon: the watchdog thread starts now
    assert rt._hb_watchdog is not None and rt._hb_watchdog.is_alive()

    # simulate a long in-flight step: the watchdog must refresh the
    # beacon while the (fake) dispatch is younger than the grace
    rt._step_began = time.monotonic()
    before = os.stat(hb).st_mtime
    time.sleep(1.6)
    assert os.stat(hb).st_mtime > before, "watchdog never touched beacon"

    # past the grace the watchdog stops vouching: beacon goes quiet
    rt._step_began = time.monotonic() - 10.0  # "dispatching" for 10s > 2.5s
    quiet_from = os.stat(hb).st_mtime
    time.sleep(1.6)
    assert os.stat(hb).st_mtime == quiet_from, (
        "watchdog kept vouching past the dispatch grace")
    rt._step_began = None
    rt.close()


# ------------------------------------------------- fleet observatory
CHAOS_CHILD = """
import os, sys, time
from heatmap_tpu.obs.xproc import publish_member_snapshot
chan = os.environ["HEATMAP_SUPERVISOR_CHANNEL"]
open(os.environ["CHILD_PID_FILE"], "w").write(str(os.getpid()))
hb = os.environ["HEATMAP_HEARTBEAT_FILE"]
while True:
    with open(hb, "w") as fh:
        fh.write(str(time.time()))
    publish_member_snapshot(chan, "c1", role="runtime",
                            freshness={"event_age_p50_s": 0.1},
                            healthz={"status": "ok", "checks": {}})
    time.sleep(0.05)
"""


def test_fleet_chaos_child_killed_mid_stream(tmp_path, monkeypatch):
    """ISSUE 6 acceptance (pinned on JAX_PLATFORMS=cpu via conftest): a
    supervisor-managed fleet with one child KILLED mid-stream yields
    /fleet/healthz degraded NAMING the dead member, and one
    flight-recorder dump per surviving member — supervisor + a
    serve-only watchdog member here — sharing a single episode id."""
    import glob
    import json
    import signal
    import threading

    from heatmap_tpu.obs.fleet import FleetAggregator
    from heatmap_tpu.obs.flightrec import FlightRecorder
    from heatmap_tpu.obs.runtimeinfo import SloWatchdog
    from heatmap_tpu.obs.xproc import (member_path,
                                       publish_member_snapshot,
                                       read_episode)

    chan = str(tmp_path / "chan")
    pid_file = tmp_path / "child.pid"
    fr_sup = tmp_path / "fr-supervisor"
    fr_srv = tmp_path / "fr-serve1"
    monkeypatch.setenv("HEATMAP_FLEET_PUBLISH_S", "0.05")
    env = {**os.environ,
           "CHILD_PID_FILE": str(pid_file),
           "HEATMAP_FLIGHTREC_DIR": str(fr_sup),
           "JAX_PLATFORMS": "cpu"}
    # long backoff: after the kill the supervisor must NOT resurrect
    # the child inside the test window — the fleet has to actually see
    # the member go dark
    sup = Supervisor(
        _child(CHAOS_CHILD),
        RestartPolicy(max_restarts=5, backoff_s=60.0, backoff_max_s=60.0,
                      term_grace_s=1.0, window_s=60.0,
                      stall_timeout_s=120.0),
        env=env, heartbeat_path=str(tmp_path / "hb"), poll_s=0.02,
        channel_path=chan)
    t = threading.Thread(target=sup.run, daemon=True)
    t.start()
    try:
        # the fleet assembles: child + supervisor member snapshots
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            if (pid_file.exists() and os.path.exists(member_path(chan, "c1"))
                    and os.path.exists(member_path(chan, "supervisor"))):
                break
            time.sleep(0.05)
        else:
            raise AssertionError("fleet never assembled")
        sup_snap = json.loads(open(member_path(chan, "supervisor")).read())
        assert sup_snap["role"] == "supervisor"
        assert "heatmap_supervisor_restarts_total" in sup_snap["metrics_text"]

        # the surviving serve-only member: publishes its snapshot and
        # runs its own SLO watchdog against the shared channel
        publish_member_snapshot(chan, "serve1", role="serve",
                                healthz={"status": "ok", "checks": {}})
        wd = SloWatchdog(None, interval_s=0.0, cooldown_s=0.0,
                         channel_path=chan, tag="serve1",
                         flightrec=FlightRecorder(str(fr_srv)))
        assert wd.check_once() is None   # healthy fleet: no episode yet

        # chaos: SIGKILL the child mid-stream (a hard death the child's
        # own recorder cannot see — exactly the supervisor's job)
        os.kill(int(pid_file.read_text()), signal.SIGKILL)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            ep = read_episode(chan)
            if ep:
                break
            time.sleep(0.05)
        else:
            raise AssertionError("supervisor never broadcast an episode")
        assert ep["origin"] == "supervisor"
        assert "child failed" in ep["reason"]
        eid = ep["episode_id"]

        # the supervisor's own dump carries the episode id
        deadline = time.monotonic() + 15
        sup_dumps = []
        while time.monotonic() < deadline and not sup_dumps:
            sup_dumps = [json.loads(open(p).read()) for p in
                         glob.glob(str(fr_sup / "flightrec-*.json"))]
            time.sleep(0.05)
        assert sup_dumps and sup_dumps[0]["episode_id"] == eid

        # the surviving member's watchdog follows the broadcast and
        # writes its correlated dump under the SAME id
        path = wd.check_once()
        assert path is not None
        srv_dump = json.loads(open(path).read())
        assert srv_dump["episode_id"] == eid

        # /fleet/healthz degrades NAMING the dead member once its
        # snapshot goes stale (it stopped publishing at the kill);
        # supervisor + serve1 keep publishing and stay fresh members
        publish_member_snapshot(chan, "serve1", role="serve",
                                healthz={"status": "ok", "checks": {}})
        agg = FleetAggregator(chan, max_age_s=0.75)
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            payload, down = agg.healthz()
            if "c1" in payload.get("stale_members", []):
                break
            time.sleep(0.1)
        else:
            raise AssertionError(
                f"dead member never went stale: {payload}")
        assert payload["status"] == "degraded" and not down
        assert payload["checks"]["member_c1"]["ok"] is False
        assert "stale" in payload["checks"]["member_c1"]["value"]
        assert "supervisor" in payload["members"]
        assert "serve1" in payload["members"]
        assert payload["episode"]["episode_id"] == eid
        txt = agg.metrics_text()
        assert 'heatmap_fleet_member_up{proc="c1",role="?"} 0' in txt
    finally:
        sup.stop()
        t.join(timeout=30)


# ------------------------------------------------- sharded fleet (ISSUE 7)
# One child = one H3-partitioned runtime shard.  These children are tiny
# scripts again: the REAL sharded runtime's checkpoint-resume and merged
# byte-identity are pinned in-process by tests/test_shard_diff.py; what
# the FleetSupervisor tests own is the LIFECYCLE — per-shard env fanout,
# per-child restart budgets, episode correlation, and the fleet surfaces
# naming the failing shard.

SHARD_COUNTING = """
import os, sys
log = os.environ["LAUNCH_LOG"] + os.environ["HEATMAP_SHARD_INDEX"]
with open(log, "a") as fh:
    fh.write(os.environ["HEATMAP_SHARDS"] + ":"
             + os.environ["HEATMAP_SHARD_INDEX"] + "\\n")
n = sum(1 for _ in open(log))
sys.exit(0 if n >= int(os.environ["SUCCEED_ON"]) else 1)
"""


def test_fleet_spawns_per_shard_env_and_restarts_each(tmp_path):
    """Every child gets HEATMAP_SHARDS=N + its own HEATMAP_SHARD_INDEX;
    restart bookkeeping is PER SHARD (each child here needs 2 launches,
    so each must be restarted once — a shared budget would conflate
    them)."""
    sup = FleetSupervisor(
        _child(SHARD_COUNTING), 3,
        RestartPolicy(max_restarts=5, **FAST),
        env={**os.environ, "LAUNCH_LOG": str(tmp_path / "log"),
             "SUCCEED_ON": "2"},
        heartbeat_dir=str(tmp_path), poll_s=0.02,
        channel_path=str(tmp_path / "chan"))
    assert sup.run() == 0
    for i in range(3):
        lines = open(str(tmp_path / "log") + str(i)).read().split()
        assert lines == [f"3:{i}", f"3:{i}"]
        assert sup.children[i].restarts == 1
        assert sup.children[i].done
    assert sup.restarts == 3


def test_fleet_one_shard_exhausting_budget_degrades_not_kills(tmp_path):
    """One shard crash-looping past its budget marks THAT shard down;
    the others still run to completion and run() returns the failing
    shard's exit code (the fleet keeps serving its remaining cell
    space instead of dying wholesale)."""
    body = """
import os, sys
i = os.environ["HEATMAP_SHARD_INDEX"]
log = os.environ["LAUNCH_LOG"] + i
with open(log, "a") as fh:
    fh.write("launch\\n")
sys.exit(3 if i == "1" else 0)
"""
    sup = FleetSupervisor(
        _child(body), 3,
        RestartPolicy(max_restarts=1, **FAST),
        env={**os.environ, "LAUNCH_LOG": str(tmp_path / "log")},
        heartbeat_dir=str(tmp_path), poll_s=0.02,
        channel_path=str(tmp_path / "chan"))
    assert sup.run() == 3
    assert sup.children[1].gave_up and not sup.children[1].done
    assert sup.children[0].done and sup.children[2].done
    # budget = max_restarts failures in window -> 2 launches of shard 1
    assert sum(1 for _ in open(str(tmp_path / "log") + "1")) == 2
    # the whole fleet did NOT give up: the channel only reports gave_up
    # when every shard exhausted its budget
    from heatmap_tpu.obs import SupervisorChannel

    assert SupervisorChannel.metrics_from(str(tmp_path / "chan"))[
        "gave_up"] == 0


def test_fleet_needs_two_shards():
    with pytest.raises(ValueError):
        FleetSupervisor(["true"], 1)


# A "runtime shard" small enough to SIGKILL deterministically: streams a
# shared corpus in batches, folds ONLY the rows its ShardMap owns into
# an append-only per-shard sink, commits its own offset file AFTER each
# batch's rows land (the offsets-after-commit discipline — replay-safe
# because the assertion dedups like the real sink's idempotent upserts),
# heartbeats + publishes a fleet member snapshot per batch, and leaves a
# departure tombstone on clean exit.
SHARD_STREAM_CHILD = """
import json, os, sys, time
import numpy as np
from heatmap_tpu.obs.xproc import publish_member_snapshot
from heatmap_tpu.stream.shardmap import ShardMap

n = int(os.environ["HEATMAP_SHARDS"])
i = int(os.environ["HEATMAP_SHARD_INDEX"])
chan = os.environ["HEATMAP_SUPERVISOR_CHANNEL"]
hb = os.environ["HEATMAP_HEARTBEAT_FILE"]
outdir = os.environ["FLEET_OUTDIR"]
batch = int(os.environ["FLEET_BATCH"])
tag = "shard%d" % i
with open(os.path.join(outdir, tag + ".launches"), "a") as fh:
    fh.write("launch\\n")
open(os.path.join(outdir, tag + ".pid"), "w").write(str(os.getpid()))
rows = [json.loads(l) for l in open(os.environ["FLEET_CORPUS"])]
lat = np.radians([r["lat"] for r in rows]).astype(np.float32)
lng = np.radians([r["lon"] for r in rows]).astype(np.float32)
own = ShardMap(n, i, 8).owned_mask(lat, lng)
off_path = os.path.join(outdir, tag + ".offset")
out_path = os.path.join(outdir, tag + ".rows")
off = int(open(off_path).read()) if os.path.exists(off_path) else 0
while off < len(rows):
    hi = min(off + batch, len(rows))
    with open(out_path, "a") as fh:
        for j in range(off, hi):
            if own[j]:
                fh.write("%d\\n" % j)
    with open(off_path + ".tmp", "w") as fh:
        fh.write(str(hi))
    os.replace(off_path + ".tmp", off_path)   # offset AFTER commit
    off = hi
    open(hb, "w").write(str(time.time()))
    publish_member_snapshot(chan, tag, role="runtime",
                            healthz={"status": "ok", "checks": {}})
    time.sleep(0.05)
publish_member_snapshot(chan, tag, role="runtime",
                        healthz={"status": "ok", "checks": {}}, left=True)
"""


def test_fleet_chaos_shard_killed_revived_converges(tmp_path, monkeypatch):
    """ISSUE 7 chaos satellite: SIGKILL one shard mid-stream — the
    restart policy revives it, the resume replays only THAT shard's own
    offsets, /fleet/healthz degrades NAMING the shard while it is dark
    and recovers, and the merged per-shard sinks converge to the
    single-shard baseline (every row exactly once across the fleet)."""
    import json
    import signal
    import threading

    import numpy as np

    from heatmap_tpu.obs.fleet import FleetAggregator
    from heatmap_tpu.obs.xproc import read_episode
    from heatmap_tpu.stream.shardmap import ShardMap

    monkeypatch.setenv("HEATMAP_FLEET_PUBLISH_S", "0.05")
    outdir = tmp_path / "out"
    outdir.mkdir()
    corpus = tmp_path / "corpus.jsonl"
    rng = np.random.default_rng(29)
    rows = [{"lat": float(rng.uniform(42.3, 42.5)),
             "lon": float(rng.uniform(-71.2, -71.0))} for _ in range(160)]
    with open(corpus, "w") as fh:
        for r in rows:
            fh.write(json.dumps(r) + "\n")
    chan = str(tmp_path / "chan")
    env = {**os.environ, "FLEET_OUTDIR": str(outdir),
           "FLEET_CORPUS": str(corpus), "FLEET_BATCH": "4",
           "JAX_PLATFORMS": "cpu"}
    # backoff ~3s: wide enough for the fleet to SEE the dead member go
    # stale before the revival even on a loaded host, short enough to
    # keep the test fast
    sup = FleetSupervisor(
        _child(SHARD_STREAM_CHILD), 2,
        RestartPolicy(max_restarts=5, backoff_s=3.0, backoff_max_s=3.0,
                      term_grace_s=1.0, window_s=60.0,
                      stall_timeout_s=120.0),
        env=env, heartbeat_dir=str(tmp_path), poll_s=0.02,
        channel_path=chan)
    rcs: list = []
    t = threading.Thread(target=lambda: rcs.append(sup.run()), daemon=True)
    t.start()
    try:
        # wait until shard 1 is genuinely MID-stream, then SIGKILL it
        off1 = outdir / "shard1.offset"
        pid1 = outdir / "shard1.pid"
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if off1.exists() and 0 < int(off1.read_text()) < len(rows) - 8:
                break
            time.sleep(0.01)
        else:
            raise AssertionError("shard1 never got mid-stream")
        os.kill(int(pid1.read_text()), signal.SIGKILL)
        killed_at = int(off1.read_text())
        assert 0 < killed_at < len(rows)

        # ONE probe loop from the moment of the kill: the failure claims
        # an episode NAMING the shard, and /fleet/healthz degrades
        # naming the dead member once its snapshot goes stale (it
        # stopped publishing at the kill).  Probing both concurrently
        # matters — the degraded window only spans the restart backoff,
        # and a sequential wait could eat it on a loaded host, after
        # which the revived fleet finishes and departs cleanly
        agg = FleetAggregator(chan, max_age_s=0.5)
        deadline = time.monotonic() + 30
        ep, degraded_payload = {}, None
        while time.monotonic() < deadline:
            if not ep:
                ep = read_episode(chan)
            if degraded_payload is None:
                payload, down = agg.healthz()
                if not payload.get("checks", {}).get(
                        "member_shard1", {}).get("ok", True):
                    assert payload["status"] == "degraded" and not down
                    degraded_payload = payload
            if ep and degraded_payload is not None:
                break
            time.sleep(0.02)
        assert ep and "shard1" in ep["reason"]
        assert degraded_payload is not None, \
            "dead shard never went stale on /fleet/healthz"

        # revival: the whole fleet runs to clean completion
        t.join(timeout=120)
        assert rcs == [0]
        launches = open(outdir / "shard1.launches").read().split()
        assert len(launches) >= 2, "restart policy never revived shard1"
        assert open(outdir / "shard0.launches").read().split() == ["launch"]

        # the resume replayed only shard 1's OWN offsets: shard 0 was
        # never killed, so its append-only sink holds exactly its owned
        # rows once; shard 1 may replay at most the one batch whose
        # offset commit the SIGKILL could have preempted
        lat = np.radians([r["lat"] for r in rows]).astype(np.float32)
        lng = np.radians([r["lon"] for r in rows]).astype(np.float32)
        owned = [np.flatnonzero(ShardMap(2, i, 8).owned_mask(lat, lng))
                 for i in range(2)]
        got0 = [int(x) for x in open(outdir / "shard0.rows").read().split()]
        got1 = [int(x) for x in open(outdir / "shard1.rows").read().split()]
        assert got0 == list(owned[0])
        assert len(got1) - len(set(got1)) <= 4  # <= one replayed batch
        # merged sinks converge to the single-shard baseline: every row
        # exactly once across the fleet (dedup = the sink's idempotent
        # upsert), cell spaces disjoint
        assert sorted(set(got0) | set(got1)) == list(range(len(rows)))
        assert not set(got0) & set(got1)

        # recovered: the supervisor's final control-plane verdict shows
        # both shards done
        from heatmap_tpu.obs.xproc import member_path

        snap = json.loads(open(member_path(chan, "supervisor")).read())
        assert snap["healthz"]["status"] == "ok"
        assert snap["healthz"]["checks"]["shard0"]["value"] == "done"
        assert snap["healthz"]["checks"]["shard1"]["value"] == "done"
    finally:
        sup.stop()
        t.join(timeout=30)
