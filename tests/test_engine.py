"""Golden micro-batch tests: device aggregation vs. a numpy dict aggregator.

SURVEY.md §4(b): feed synthetic event arrays through the device aggregation
and assert (cellId, window) -> (count, avgSpeed, ...) exactly.
"""

import numpy as np
import pytest

from heatmap_tpu.engine import AggParams, TileState, init_state, merge_batch
from heatmap_tpu.engine.state import EMPTY_KEY_HI
from heatmap_tpu.engine.step import snap_and_window

PARAMS = AggParams(res=8, window_s=300, emit_capacity=512)


def make_batch(rng, n, t0=1_700_000_000, spread_s=600, nan_frac=0.0):
    lat = np.radians(rng.uniform(42.2, 42.5, n)).astype(np.float32)
    lng = np.radians(rng.uniform(-71.3, -70.8, n)).astype(np.float32)
    speed = rng.uniform(0, 120, n).astype(np.float32)
    ts = (t0 + rng.integers(0, spread_s, n)).astype(np.int32)
    valid = np.ones(n, bool)
    if nan_frac:
        valid[rng.random(n) < nan_frac] = False
    return lat, lng, speed, ts, valid


class DictAgg:
    """Host-side oracle mirroring the reference groupBy semantics
    (heatmap_stream.py:112-133) plus watermark eviction."""

    def __init__(self, params):
        self.p = params
        self.groups = {}

    def feed(self, keys_hi, keys_lo, ws, speed, lat_deg, lon_deg, valid, cutoff):
        # evict closed windows first (mirrors merge_batch ordering)
        self.groups = {
            k: v for k, v in self.groups.items()
            if k[2] + self.p.window_s > cutoff
        }
        touched = set()
        for i in range(len(ws)):
            if not valid[i]:
                continue
            if ws[i] + self.p.window_s <= cutoff:
                continue  # late
            k = (int(keys_hi[i]), int(keys_lo[i]), int(ws[i]))
            g = self.groups.setdefault(k, [0, 0.0, 0.0, 0.0, 0.0])
            g[0] += 1
            g[1] += float(speed[i])
            g[2] += float(speed[i]) ** 2
            g[3] += float(lat_deg[i])
            g[4] += float(lon_deg[i])
            touched.add(k)
        return touched


def run_both(rng, n_batches=4, n=256, cap=4096, cutoff_fn=None, nan_frac=0.0,
             params=PARAMS):
    state = init_state(cap, hist_bins=0)
    oracle = DictAgg(params)
    all_touched = []
    for b in range(n_batches):
        lat, lng, speed, ts, valid = make_batch(
            rng, n, t0=1_700_000_000 + b * 120, nan_frac=nan_frac
        )
        cutoff = np.int32(cutoff_fn(b) if cutoff_fn else -2**31)
        hi, lo, ws = snap_and_window(lat, lng, ts, valid, params)
        hi, lo, ws = np.asarray(hi), np.asarray(lo), np.asarray(ws)
        lat_deg = np.degrees(lat.astype(np.float64)).astype(np.float32)
        lon_deg = np.degrees(lng.astype(np.float64)).astype(np.float32)
        state, emit, stats = merge_batch(
            state, hi, lo, ws, speed, lat_deg, lon_deg, ts, valid, cutoff, params
        )
        touched = oracle.feed(hi, lo, ws, speed, lat_deg, lon_deg, valid, cutoff)
        all_touched.append((emit, touched))
    return state, oracle, all_touched, stats


def state_as_dict(state):
    """Live groups as absolute moments.  The slab stores RESIDUAL sums
    about per-group anchors (engine.state.TileState); reconstructing the
    absolute sums in f64 here (Σv = a·c + Σr, Σv² = Σr² + 2aΣr + c·a²)
    is itself a differential check of the anchor algebra."""
    out = {}
    hi = np.asarray(state.key_hi)
    live = hi != np.uint32(0xFFFFFFFF)
    cnt = np.asarray(state.count)
    rs = np.asarray(state.sum_speed, dtype=np.float64)
    rs2 = np.asarray(state.sum_speed2, dtype=np.float64)
    rla = np.asarray(state.sum_lat, dtype=np.float64)
    rlo = np.asarray(state.sum_lon, dtype=np.float64)
    a_s = np.asarray(state.anchor_speed, dtype=np.float64)
    a_la = np.asarray(state.anchor_lat, dtype=np.float64)
    a_lo = np.asarray(state.anchor_lon, dtype=np.float64)
    for i in np.nonzero(live)[0]:
        k = (int(hi[i]), int(np.asarray(state.key_lo)[i]),
             int(np.asarray(state.key_ws)[i]))
        c = int(cnt[i])
        out[k] = [
            c,
            a_s[i] * c + rs[i],
            rs2[i] + 2.0 * a_s[i] * rs[i] + c * a_s[i] ** 2,
            a_la[i] * c + rla[i],
            a_lo[i] * c + rlo[i],
        ]
    return out


def assert_groups_equal(got, want, rtol=2e-5):
    assert set(got) == set(want)
    for k, g in got.items():
        w = want[k]
        assert g[0] == w[0], (k, g, w)  # exact count
        np.testing.assert_allclose(g[1:], w[1:], rtol=rtol, atol=1e-3)


def test_multi_batch_exact_aggregation(rng):
    state, oracle, _, stats = run_both(rng)
    assert_groups_equal(state_as_dict(state), oracle.groups)
    assert int(stats.n_active) == len(oracle.groups)
    assert int(stats.state_overflow) == 0


def test_invalid_rows_excluded(rng):
    state, oracle, _, _ = run_both(rng, nan_frac=0.3)
    assert_groups_equal(state_as_dict(state), oracle.groups)


def test_sorted_invariant_and_empties_at_tail(rng):
    state, _, _, _ = run_both(rng)
    hi = np.asarray(state.key_hi)
    lo = np.asarray(state.key_lo)
    ws = np.asarray(state.key_ws)
    live = hi != np.uint32(0xFFFFFFFF)
    # slab order is the engine's compressed sort key (wix12 | hi20, lo)
    wix = (ws[live].astype(np.int64) // PARAMS.window_s).astype(np.uint32) & 0xFFF
    k1 = (wix.astype(np.uint64) << 20) | (hi[live].astype(np.uint64) & 0xFFFFF)
    composite = list(zip(k1.tolist(), lo[live].astype(np.uint64).tolist()))
    assert composite == sorted(composite)
    n = live.sum()
    assert not live[n:].any()


def test_watermark_eviction_and_late_drop(rng):
    # cutoff advances past the first batches' windows
    t0 = 1_700_000_000
    win = PARAMS.window_s

    def cutoff(b):
        # batch 3 carries a watermark that closes every window before t0+600
        return t0 + 600 if b == 3 else -2**31

    state, oracle, _, stats = run_both(rng, n_batches=4, cutoff_fn=cutoff)
    got = state_as_dict(state)
    assert_groups_equal(got, oracle.groups)
    assert all(k[2] + win > t0 + 600 for k in got)
    assert int(stats.n_evicted) > 0 or int(stats.n_late) > 0


def test_emit_matches_touched_groups(rng):
    state, oracle, touched_log, _ = run_both(rng, n_batches=2)
    emit, touched = touched_log[-1]
    valid = np.asarray(emit.valid)
    got_keys = {
        (int(np.asarray(emit.key_hi)[i]), int(np.asarray(emit.key_lo)[i]),
         int(np.asarray(emit.key_ws)[i]))
        for i in np.nonzero(valid)[0]
    }
    assert got_keys == touched
    assert int(emit.n_emitted) == len(touched)
    assert not bool(emit.overflowed)
    # emitted aggregates equal current state values
    sd = state_as_dict(state)
    for i in np.nonzero(valid)[0]:
        k = (int(np.asarray(emit.key_hi)[i]), int(np.asarray(emit.key_lo)[i]),
             int(np.asarray(emit.key_ws)[i]))
        assert int(np.asarray(emit.count)[i]) == sd[k][0]


def test_emit_overflow_flag(rng):
    params = AggParams(res=8, window_s=300, emit_capacity=4)
    state = init_state(512, 0)
    lat, lng, speed, ts, valid = make_batch(rng, 256)
    hi, lo, ws = snap_and_window(lat, lng, ts, valid, params)
    state, emit, _ = merge_batch(
        state, np.asarray(hi), np.asarray(lo), np.asarray(ws), speed,
        np.degrees(lat), np.degrees(lng), ts, valid, np.int32(-2**31), params
    )
    assert bool(emit.overflowed)
    assert int(emit.n_emitted) > 4
    assert np.asarray(emit.valid).sum() == 4


def test_state_overflow_counted(rng):
    state = init_state(8, 0)  # tiny capacity
    lat, lng, speed, ts, valid = make_batch(rng, 512)
    hi, lo, ws = snap_and_window(lat, lng, ts, valid, PARAMS)
    state, _, stats = merge_batch(
        state, np.asarray(hi), np.asarray(lo), np.asarray(ws), speed,
        np.degrees(lat), np.degrees(lng), ts, valid, np.int32(-2**31), PARAMS
    )
    assert int(stats.state_overflow) > 0
    assert int(stats.n_active) == 8


def test_speed_histogram(rng):
    params = AggParams(res=8, window_s=300, emit_capacity=128, speed_hist_max=128.0)
    state = init_state(2048, hist_bins=16)
    lat, lng, speed, ts, valid = make_batch(rng, 512)
    hi, lo, ws = snap_and_window(lat, lng, ts, valid, params)
    state, emit, _ = merge_batch(
        state, np.asarray(hi), np.asarray(lo), np.asarray(ws), speed,
        np.degrees(lat), np.degrees(lng), ts, valid, np.int32(-2**31), params
    )
    hist = np.asarray(state.hist)
    count = np.asarray(state.count)
    # per-row histogram mass equals the row count
    np.testing.assert_array_equal(hist.sum(axis=1), count)
    # total mass = number of valid events
    assert hist.sum() == valid.sum()
    # oracle per-bin check
    keys = np.stack([np.asarray(hi), np.asarray(lo), np.asarray(ws)], 1)
    bins = np.clip((speed / (128.0 / 16)).astype(int), 0, 15)
    from collections import Counter

    oracle = Counter()
    for i in range(len(speed)):
        oracle[(tuple(keys[i]), bins[i])] += 1
    shi = np.asarray(state.key_hi)
    for r in np.nonzero(shi != np.uint32(0xFFFFFFFF))[0]:
        for b in range(16):
            want = oracle.get(((np.asarray(state.key_hi)[r],
                                np.asarray(state.key_lo)[r],
                                np.asarray(state.key_ws)[r]), b), 0)
            assert hist[r, b] == want

def test_hot_cell_precision_1m(rng):
    """VERDICT r2 #4 acceptance: fold 1M events into one hot cell across
    many batches and match a host f64 oracle — centroid within 1e-6 deg,
    avgSpeed within 0.01 km/h.  Absolute f32 sums cannot pass this (Σlat
    ≈ 4.2e7 has ulp 4 → ~2e-6 deg/event even correctly rounded); the
    residual-anchor accumulation with Kahan compensation must."""
    params = AggParams(res=8, window_s=300, emit_capacity=64)
    state = init_state(256, hist_bins=0)
    n, batches = 1 << 14, 64                      # 1,048,576 events
    t0 = np.int32(1_700_000_000)
    # all events inside one res-8 cell (~0.005 deg): center + tiny jitter
    base_lat, base_lon = 42.360100, -71.058900
    f64 = np.zeros(4)                              # Σv, Σv², Σlat, Σlon
    n_tot = 0
    for b in range(batches):
        lat_deg = (base_lat + rng.uniform(-4e-4, 4e-4, n)).astype(np.float32)
        lon_deg = (base_lon + rng.uniform(-4e-4, 4e-4, n)).astype(np.float32)
        # constant-ish speeds are the f32 worst case: partial sums grow
        # monotonically so naive rounding bias is maximal
        speed = (30.0 + 0.5 * (np.arange(n) % 2)).astype(np.float32)
        ts = np.full(n, t0, np.int32)
        valid = np.ones(n, bool)
        lat = np.radians(lat_deg.astype(np.float64)).astype(np.float32)
        lng = np.radians(lon_deg.astype(np.float64)).astype(np.float32)
        hi, lo, ws = snap_and_window(lat, lng, ts, valid, params)
        state, emit, stats = merge_batch(
            state, np.asarray(hi), np.asarray(lo), np.asarray(ws), speed,
            lat_deg, lon_deg, ts, valid, np.int32(-2**31), params)
        f64 += [speed.astype(np.float64).sum(),
                (speed.astype(np.float64) ** 2).sum(),
                lat_deg.astype(np.float64).sum(),
                lon_deg.astype(np.float64).sum()]
        n_tot += n
    groups = state_as_dict(state)
    # the jitter stays well inside one cell -> exactly one group
    assert len(groups) == 1 and next(iter(groups.values()))[0] == n_tot
    c, s_v, s_v2, s_la, s_lo = next(iter(groups.values()))
    assert abs(s_la / c - f64[2] / n_tot) < 1e-6       # centroid lat
    assert abs(s_lo / c - f64[3] / n_tot) < 1e-6       # centroid lon
    assert abs(s_v / c - f64[0] / n_tot) < 0.01        # avgSpeed
    dev_var = s_v2 / c - (s_v / c) ** 2
    ora_var = f64[1] / n_tot - (f64[0] / n_tot) ** 2
    assert abs(dev_var ** 0.5 - ora_var ** 0.5) < 0.02  # stddev
