"""MultiAggregator (engine.multi): the fused every-(res,window)-pair step
must agree exactly with independent SingleAggregators driven pair by pair,
and its packed head rows must carry the per-pair step stats."""

import numpy as np
import pytest

from heatmap_tpu.engine import AggParams
from heatmap_tpu.engine.multi import MultiAggregator, stats_from_packed
from heatmap_tpu.engine.single import SingleAggregator
from heatmap_tpu.engine.step import unpack_emit

from tests.test_engine import make_batch

PAIRS = [(7, 300), (8, 60), (8, 300), (9, 900)]
CAP = 4096
N = 512
BINS = 16


def _emit_as_dict(e):
    """unpacked emit -> {key: (count, sums..., p95)} over valid rows."""
    out = {}
    for i in np.nonzero(e["valid"])[0]:
        k = (int(e["key_hi"][i]), int(e["key_lo"][i]), int(e["key_ws"][i]))
        out[k] = (
            int(e["count"][i]),
            round(float(e["sum_speed"][i]), 3),
            round(float(e["sum_speed2"][i]), 1),
            round(float(e["sum_lat"][i]), 4),
            round(float(e["sum_lon"][i]), 4),
            round(float(e["p95"][i]), 3),
        )
    return out


@pytest.mark.slow  # tier-1 budget: see pyproject markers
def test_multi_matches_singles(rng):
    multi = MultiAggregator(PAIRS, capacity=CAP, batch_size=N,
                            emit_capacity=N, hist_bins=BINS)
    singles = {
        (r, w): SingleAggregator(
            AggParams(res=r, window_s=w, emit_capacity=N),
            capacity=CAP, batch_size=N, hist_bins=BINS,
        )
        for r, w in PAIRS
    }
    max_ts = -(2**31)
    for b in range(4):
        lat, lng, speed, ts, valid = make_batch(
            rng, N, t0=1_700_000_000 + b * 400, nan_frac=0.1)
        cutoff = max_ts - 600 if max_ts > -(2**31) else -(2**31)
        packed = multi.step_packed_all(lat, lng, speed, ts, valid, cutoff)
        bufs = np.asarray(packed)
        assert bufs.shape == (len(PAIRS), N + 1, 13)
        for idx, (r, w) in enumerate(PAIRS):
            sp, s_stats = singles[(r, w)].step_packed(
                lat, lng, speed, ts, valid, cutoff)
            e_multi = unpack_emit(bufs[idx])
            e_single = unpack_emit(np.asarray(sp))
            assert _emit_as_dict(e_multi) == _emit_as_dict(e_single), (r, w, b)
            m_stats = stats_from_packed(bufs[idx])
            s_stats = {f: int(np.asarray(getattr(s_stats, f)))
                       for f in ("n_valid", "n_late", "n_evicted", "n_active",
                                 "state_overflow", "batch_max_ts")}
            for f, v in s_stats.items():
                assert getattr(m_stats, f) == v, (r, w, b, f)
        max_ts = max(max_ts, stats_from_packed(bufs[0]).batch_max_ts)

    # states agree pairwise too (same slab after the same folds)
    for idx, (r, w) in enumerate(PAIRS):
        got = multi.view(r, w).snapshot()
        want = singles[(r, w)].snapshot()
        for g, s in zip(got, want):
            np.testing.assert_array_equal(np.asarray(g), np.asarray(s))


@pytest.mark.slow  # tier-1 budget: see pyproject markers
def test_pair_view_checkpoint_roundtrip(rng):
    multi = MultiAggregator(PAIRS[:2], capacity=CAP, batch_size=N,
                            emit_capacity=N, hist_bins=0)
    lat, lng, speed, ts, valid = make_batch(rng, N)
    multi.step_packed_all(lat, lng, speed, ts, valid, -(2**31))
    snap = {p: multi.view(*p).snapshot() for p in PAIRS[:2]}

    fresh = MultiAggregator(PAIRS[:2], capacity=CAP, batch_size=N,
                            emit_capacity=N, hist_bins=0)
    for p in PAIRS[:2]:
        fresh.view(*p).restore(snap[p])
    for a, b in zip(multi.states, fresh.states):
        for g, s in zip(a, b):
            np.testing.assert_array_equal(np.asarray(g), np.asarray(s))

    # shape mismatch must refuse (config drift protection)
    import pytest

    small = MultiAggregator(PAIRS[:2], capacity=CAP // 2, batch_size=N,
                            emit_capacity=N, hist_bins=0)
    with pytest.raises(ValueError):
        small.view(*PAIRS[0]).restore(snap[PAIRS[0]])
