"""Differential tests for the C++ columnar->BSON tile encoder
(native/tile_ops.cpp) against the portable Python doc builder
(sink.base.packed_tile_docs), plus the OP_MSG document-sequence write path
end-to-end against the wire-level mock mongod."""

import numpy as np
import pytest

from heatmap_tpu.native import NativeTileOps
from heatmap_tpu.sink import bson
from heatmap_tpu.sink.base import TilePackMeta, packed_tile_docs

pytestmark = pytest.mark.skipif(
    not NativeTileOps.available(), reason="no C++ toolchain")

META = TilePackMeta(city="bos", grid="h3r8", window_s=300, ttl_minutes=45,
                    window_minutes_tag=0, with_p95=True)


def make_body(rng, n, invalid_frac=0.15):
    body = np.zeros((n, 13), np.uint32)
    body[:, 0] = rng.integers(0, 2**31, n)          # key_hi (bit 31 clear)
    body[:, 1] = rng.integers(0, 2**32, n)          # key_lo
    ws = (1_700_000_000 + rng.integers(0, 864, n) * 100).astype(np.int32)
    body[:, 2] = ws.view(np.uint32)
    body[:, 3] = rng.integers(0, 50, n)             # count (some zeros)
    # residual sums (4-7) about the anchor lanes (10-12) — small
    # residual magnitudes, realistic anchors (engine.state.TileState)
    for col, lo, hi in ((4, -50.0, 5000.0), (5, 0, 1e5),
                        (6, -0.01 * 40, 0.01 * 40),
                        (7, -0.01 * 40, 0.01 * 40),
                        (9, 0, 250.0), (10, 0, 200.0),
                        (11, -90.0, 90.0), (12, -180.0, 180.0)):
        body[:, col] = rng.uniform(lo, hi, n).astype(np.float32).view(np.uint32)
    body[:, 8] = (rng.random(n) > invalid_frac).astype(np.uint32)
    return body


def doc_from_op(op: dict) -> dict:
    assert op["upsert"] is True
    assert set(op) == {"q", "u", "upsert"}
    doc = op["u"]["$set"]
    assert op["q"] == {"_id": doc["_id"]}
    return doc


def decode_ops(ops: bytes, end_offsets) -> list[dict]:
    out, start = [], 0
    for end in end_offsets:
        out.append(doc_from_op(bson.decode(ops[start:int(end)])))
        start = int(end)
    assert start == len(ops)
    return out


@pytest.mark.parametrize("meta", [
    META,
    META._replace(grid="h3r9m1", window_s=60, window_minutes_tag=1),
    META._replace(with_p95=False, city="global-city"),
])
def test_native_matches_python(rng, meta):
    enc = NativeTileOps()
    body = make_body(rng, 257)
    ops, offsets, n = enc.encode(body, meta.city, meta.grid, meta.window_s,
                                 meta.ttl_minutes, meta.window_minutes_tag,
                                 meta.with_p95)
    got = decode_ops(ops, offsets)
    want = packed_tile_docs(body, meta)
    assert n == len(want) > 50
    assert len(got) == n
    for g, w in zip(got, want):
        assert list(g) == list(w), "field order must match"
        for k in w:
            if isinstance(w[k], float):
                assert g[k] == pytest.approx(w[k], rel=1e-15, abs=1e-300), k
            else:
                assert g[k] == w[k], k


def test_empty_and_all_invalid(rng):
    enc = NativeTileOps()
    body = make_body(rng, 16)
    body[:, 8] = 0
    ops, offsets, n = enc.encode(body, "bos", "h3r8", 300, 45, 0, True)
    assert n == 0 and len(ops) == 0 and len(offsets) == 0
    ops, offsets, n = enc.encode(np.zeros((0, 13), np.uint32),
                                 "bos", "h3r8", 300, 45, 0, True)
    assert n == 0


def test_docseq_write_path_matches_python_path(rng):
    """MongoStore.upsert_tiles_packed (C++ encode + kind-1 doc sequence)
    must leave the mock server in exactly the state the Python
    upsert_tiles path produces — across multiple 1000-op chunks."""
    from heatmap_tpu.sink.mongo import MongoStore, _WireBackend
    from heatmap_tpu.testing.mock_mongod import MockMongod

    body = make_body(rng, 2500, invalid_frac=0.05)
    # make keys unique so doc counts are deterministic
    body[:, 1] = np.arange(2500, dtype=np.uint32)
    with MockMongod() as uri_a, MockMongod() as uri_b:
        # explicit wire backend: the native docseq path must engage even on
        # machines where pymongo is installed (it would win the autoprobe)
        store_a = MongoStore(uri_a, "mobility", ensure_indexes=False,
                             backend=_WireBackend(uri_a, "mobility"))
        store_b = MongoStore(uri_b, "mobility", ensure_indexes=False,
                             backend=_WireBackend(uri_b, "mobility"))
        n_a = store_a.upsert_tiles_packed(body, META)
        assert store_a._tile_ops is not None, "native path must engage"
        n_b = store_b.upsert_tiles(packed_tile_docs(body, META))
        assert n_a == n_b > 1000

        a = {d["_id"]: d for d in store_a._b.find("tiles", {})}
        b = {d["_id"]: d for d in store_b._b.find("tiles", {})}
        assert set(a) == set(b)
        for k in a:
            ga, gb = a[k], b[k]
            assert list(ga) == list(gb)
            for f in ga:
                if isinstance(ga[f], float):
                    assert ga[f] == pytest.approx(gb[f], rel=1e-15), (k, f)
                else:
                    assert ga[f] == gb[f], (k, f)
        store_a.close()
        store_b.close()


def test_default_store_packed_path(rng):
    """Stores without a native path (MemoryStore) take the portable
    packed->docs fallback and agree with explicit doc upserts."""
    from heatmap_tpu.sink.memory import MemoryStore

    body = make_body(rng, 64)
    s1, s2 = MemoryStore(), MemoryStore()
    n1 = s1.upsert_tiles_packed(body, META)
    n2 = s2.upsert_tiles(packed_tile_docs(body, META))
    assert n1 == n2
    ws = s1.latest_window_start()
    a = sorted(s1.tiles_in_window(ws), key=lambda d: d["_id"])
    b = sorted(s2.tiles_in_window(ws), key=lambda d: d["_id"])
    assert a == b


def test_oversized_city_never_drops_rows(rng):
    """Review regression: a long city/grid must not silently skip rows —
    the native path resizes its buffers and emits every doc."""
    enc = NativeTileOps()
    meta = META._replace(city="c" * 200, grid="g" * 64)
    body = make_body(rng, 64, invalid_frac=0.0)
    body[:, 3] = np.maximum(body[:, 3], 1)  # all counts positive
    ops, offsets, n = enc.encode(body, meta.city, meta.grid, meta.window_s,
                                 meta.ttl_minutes, 0, True)
    want = packed_tile_docs(body, meta)
    assert n == len(want) == 64
    got = decode_ops(ops, offsets)
    assert [g["_id"] for g in got] == [w["_id"] for w in want]
