"""Bit-level serialization: pack_emit/unpack_emit round-trip, device p95 vs
host p95, and the future-event (clock-skew) drop guard."""

import numpy as np
import pytest

from heatmap_tpu.engine import AggParams, init_state, merge_batch
from heatmap_tpu.engine.single import SingleAggregator
from heatmap_tpu.engine.step import (
    FUTURE_WINDOWS,
    p95_from_hist_device,
    pack_emit,
    snap_and_window,
    unpack_emit,
)
from tests.test_engine import make_batch


def _p95_from_hist(hist_row: np.ndarray, count: int, hist_max: float) -> float:
    """Host reference for the device p95: 95th percentile by linear
    interpolation inside the hit bin (oracle for p95_from_hist_device)."""
    if count <= 0 or hist_row.size == 0:
        return 0.0
    b = hist_row.size
    bin_w = hist_max / b
    target = 0.95 * count
    cum = np.cumsum(hist_row)
    i = int(np.searchsorted(cum, target))
    if i >= b:
        return float(hist_max)
    prev = float(cum[i - 1]) if i > 0 else 0.0
    in_bin = float(hist_row[i])
    frac = (target - prev) / in_bin if in_bin > 0 else 0.0
    return (i + frac) * bin_w

PARAMS = AggParams(res=8, window_s=300, emit_capacity=512,
                   speed_hist_max=256.0)


def _run_one(rng, bins=16):
    state = init_state(4096, hist_bins=bins)
    lat, lng, speed, ts, valid = make_batch(rng, 256)
    hi, lo, ws = snap_and_window(lat, lng, ts, valid, PARAMS)
    state, emit, _ = merge_batch(
        state, np.asarray(hi), np.asarray(lo), np.asarray(ws), speed,
        np.degrees(lat), np.degrees(lng), ts, valid, np.int32(-2**31), PARAMS
    )
    return emit


def test_pack_unpack_roundtrip(rng):
    emit = _run_one(rng)
    got = unpack_emit(pack_emit(emit, PARAMS.speed_hist_max))
    for field in ("key_hi", "key_lo", "key_ws", "count", "valid"):
        np.testing.assert_array_equal(got[field], np.asarray(getattr(emit, field)))
    for field in ("sum_speed", "sum_speed2", "sum_lat", "sum_lon"):
        # bitcast round trip must be exact, not approximately equal
        np.testing.assert_array_equal(got[field], np.asarray(getattr(emit, field)))
    assert got["n_emitted"] == int(np.asarray(emit.n_emitted))
    assert got["overflowed"] == bool(np.asarray(emit.overflowed))


def test_device_p95_matches_host(rng):
    emit = _run_one(rng, bins=16)
    dev = np.asarray(p95_from_hist_device(emit.hist, emit.count, 256.0))
    hist = np.asarray(emit.hist)
    count = np.asarray(emit.count)
    for i in range(len(count)):
        host = _p95_from_hist(hist[i], int(count[i]), 256.0)
        assert dev[i] == pytest.approx(host, abs=1e-3), i
    # packed lane carries the same values
    got = unpack_emit(pack_emit(emit, 256.0))
    np.testing.assert_allclose(got["p95"], dev, atol=1e-5)


def test_future_events_dropped_with_watermark(rng):
    agg_params = AggParams(res=8, window_s=300, emit_capacity=512)
    agg = SingleAggregator(agg_params, capacity=4096, batch_size=256)
    t0 = 1_700_000_000
    lat, lng, speed, ts, valid = make_batch(rng, 256, t0=t0)
    # half the events jump ~15 days into the future (wix-alias poison)
    ts = ts.copy()
    ts[::2] = t0 + (FUTURE_WINDOWS + 7) * 300
    cutoff = np.int32(t0 - 600)  # active watermark
    _, stats = agg.step(lat, lng, speed, ts, valid, cutoff)
    assert int(stats.n_late) == 128
    assert int(stats.n_valid) == 128


def test_future_events_kept_without_watermark(rng):
    # watermark off (bounded replay): future guard must not engage
    agg_params = AggParams(res=8, window_s=300, emit_capacity=512)
    agg = SingleAggregator(agg_params, capacity=4096, batch_size=256)
    lat, lng, speed, ts, valid = make_batch(rng, 256)
    _, stats = agg.step(lat, lng, speed, ts, valid, -2**31)
    assert int(stats.n_valid) == 256
    assert int(stats.n_late) == 0

def test_p95_error_bound_one_bin(rng):
    """Config.speed_hist_bins' stated accuracy: interpolated hist-p95 is
    within ONE BIN WIDTH of the exact sample p95 for any in-range
    distribution, and saturates to hist_max when the true p95 exceeds the
    range (VERDICT r2 #7 — the bound OpenSky's preset relies on)."""
    dists = {
        "uniform": lambda n: rng.uniform(0, 200, n),
        "normal": lambda n: np.clip(rng.normal(60, 20, n), 0, None),
        "bimodal": lambda n: np.concatenate(
            [rng.normal(30, 5, n // 2), rng.normal(150, 15, n - n // 2)]),
        "heavy_tail": lambda n: np.minimum(rng.exponential(40, n), 250.0),
        "constant": lambda n: np.full(n, 87.3),
    }
    for bins, hist_max in ((64, 256.0), (128, 1280.0), (32, 256.0)):
        bin_w = hist_max / bins
        for name, make in dists.items():
            speeds = make(5000).astype(np.float32)
            ev_bin = np.clip((speeds / bin_w).astype(np.int64), 0, bins - 1)
            hist = np.bincount(ev_bin, minlength=bins)[None, :].astype(np.int32)
            got = float(np.asarray(p95_from_hist_device(
                hist, np.array([len(speeds)], np.int32), hist_max))[0])
            exact = float(np.percentile(speeds, 95))
            assert abs(got - exact) <= bin_w + 1e-3, \
                (name, bins, hist_max, got, exact)
    # saturation: a distribution entirely beyond the range pegs the
    # reported p95 at the top of the range (within one bin), not garbage
    speeds = rng.uniform(900, 1100, 4000).astype(np.float32)
    ev_bin = np.clip((speeds / 4.0).astype(np.int64), 0, 63)
    hist = np.bincount(ev_bin, minlength=64)[None, :].astype(np.int32)
    got = float(np.asarray(p95_from_hist_device(
        hist, np.array([len(speeds)], np.int32), 256.0))[0])
    assert 256.0 - 4.0 <= got <= 256.0

def test_pull_emit_prefix_equivalent(rng):
    """Live-prefix pulls (engine.step.pull_emit_prefix, the runtime's
    emit_pull=prefix discipline) must surface exactly the same live rows
    and head stats as a full transfer — rows are truncated to the
    power-of-two bucket, never reordered or lost (live rows are a prefix
    by construction)."""
    from heatmap_tpu.engine.step import pull_emit_prefix

    emit = _run_one(rng, bins=8)
    packed = pack_emit(emit, 256.0)
    full = unpack_emit(np.asarray(packed))
    pref = unpack_emit(pull_emit_prefix(packed))
    assert pref["n_emitted"] == full["n_emitted"] > 0
    assert pref["overflowed"] == full["overflowed"]
    n = full["n_emitted"]
    assert pref["valid"][:n].all() and not pref["valid"][n:].any()
    # bucket is the next power of two (bounded retrace count)
    b = len(pref["valid"])
    assert b >= n and (b & (b - 1)) == 0 or b == len(full["valid"])
    for k in ("key_hi", "key_lo", "key_ws", "count", "sum_speed",
              "sum_lat", "sum_lon", "anchor_speed", "anchor_lat",
              "anchor_lon", "p95"):
        np.testing.assert_array_equal(pref[k][:n], full[k][:n])
