"""Bit-level serialization: pack_emit/unpack_emit round-trip, device p95 vs
host p95, and the future-event (clock-skew) drop guard."""

import numpy as np
import pytest

from heatmap_tpu.engine import AggParams, init_state, merge_batch
from heatmap_tpu.engine.single import SingleAggregator
from heatmap_tpu.engine.step import (
    FUTURE_WINDOWS,
    p95_from_hist_device,
    pack_emit,
    snap_and_window,
    unpack_emit,
)
from tests.test_engine import make_batch


def _p95_from_hist(hist_row: np.ndarray, count: int, hist_max: float) -> float:
    """Host reference for the device p95: 95th percentile by linear
    interpolation inside the hit bin (oracle for p95_from_hist_device)."""
    if count <= 0 or hist_row.size == 0:
        return 0.0
    b = hist_row.size
    bin_w = hist_max / b
    target = 0.95 * count
    cum = np.cumsum(hist_row)
    i = int(np.searchsorted(cum, target))
    if i >= b:
        return float(hist_max)
    prev = float(cum[i - 1]) if i > 0 else 0.0
    in_bin = float(hist_row[i])
    frac = (target - prev) / in_bin if in_bin > 0 else 0.0
    return (i + frac) * bin_w

PARAMS = AggParams(res=8, window_s=300, emit_capacity=512,
                   speed_hist_max=256.0)


def _run_one(rng, bins=16):
    state = init_state(4096, hist_bins=bins)
    lat, lng, speed, ts, valid = make_batch(rng, 256)
    hi, lo, ws = snap_and_window(lat, lng, ts, valid, PARAMS)
    state, emit, _ = merge_batch(
        state, np.asarray(hi), np.asarray(lo), np.asarray(ws), speed,
        np.degrees(lat), np.degrees(lng), ts, valid, np.int32(-2**31), PARAMS
    )
    return emit


def test_pack_unpack_roundtrip(rng):
    emit = _run_one(rng)
    got = unpack_emit(pack_emit(emit, PARAMS.speed_hist_max))
    for field in ("key_hi", "key_lo", "key_ws", "count", "valid"):
        np.testing.assert_array_equal(got[field], np.asarray(getattr(emit, field)))
    for field in ("sum_speed", "sum_speed2", "sum_lat", "sum_lon"):
        # bitcast round trip must be exact, not approximately equal
        np.testing.assert_array_equal(got[field], np.asarray(getattr(emit, field)))
    assert got["n_emitted"] == int(np.asarray(emit.n_emitted))
    assert got["overflowed"] == bool(np.asarray(emit.overflowed))


def test_device_p95_matches_host(rng):
    emit = _run_one(rng, bins=16)
    dev = np.asarray(p95_from_hist_device(emit.hist, emit.count, 256.0))
    hist = np.asarray(emit.hist)
    count = np.asarray(emit.count)
    for i in range(len(count)):
        host = _p95_from_hist(hist[i], int(count[i]), 256.0)
        assert dev[i] == pytest.approx(host, abs=1e-3), i
    # packed lane carries the same values
    got = unpack_emit(pack_emit(emit, 256.0))
    np.testing.assert_allclose(got["p95"], dev, atol=1e-5)


def test_future_events_dropped_with_watermark(rng):
    agg_params = AggParams(res=8, window_s=300, emit_capacity=512)
    agg = SingleAggregator(agg_params, capacity=4096, batch_size=256)
    t0 = 1_700_000_000
    lat, lng, speed, ts, valid = make_batch(rng, 256, t0=t0)
    # half the events jump ~15 days into the future (wix-alias poison)
    ts = ts.copy()
    ts[::2] = t0 + (FUTURE_WINDOWS + 7) * 300
    cutoff = np.int32(t0 - 600)  # active watermark
    _, stats = agg.step(lat, lng, speed, ts, valid, cutoff)
    assert int(stats.n_late) == 128
    assert int(stats.n_valid) == 128


def test_future_events_kept_without_watermark(rng):
    # watermark off (bounded replay): future guard must not engage
    agg_params = AggParams(res=8, window_s=300, emit_capacity=512)
    agg = SingleAggregator(agg_params, capacity=4096, batch_size=256)
    lat, lng, speed, ts, valid = make_batch(rng, 256)
    _, stats = agg.step(lat, lng, speed, ts, valid, -2**31)
    assert int(stats.n_valid) == 256
    assert int(stats.n_late) == 0
