"""Runtime introspection (obs.runtimeinfo / obs.prof): compile/retrace
tracking, memory telemetry, the stack sampler, and the SLO-triggered
auto-capture watchdog — including the acceptance scenarios: a forced
post-warmup retrace and a forced memory-watermark breach each produce
(a) a visible metric, (b) a /healthz degradation, and (c) an automatic
enriched flight-recorder dump, all on JAX_PLATFORMS=cpu."""

import json
import time

import jax
import jax.numpy as jnp
import pytest

from heatmap_tpu.config import load_config
from heatmap_tpu.obs.prof import StackSampler
from heatmap_tpu.obs.registry import Registry
from heatmap_tpu.obs.runtimeinfo import (
    CompileTracker,
    MemoryMonitor,
    RuntimeIntrospection,
    SloWatchdog,
    healthz_checks,
)
from heatmap_tpu.sink import MemoryStore
from heatmap_tpu.stream import MicroBatchRuntime
from heatmap_tpu.stream.source import MemorySource


# ------------------------------------------------------------ units
def test_compile_tracker_counts_and_retrace_detection():
    reg = Registry()
    tr = CompileTracker(reg, warmup=3)
    f = tr.wrap("f", jax.jit(lambda x: x + 1))
    for _ in range(3):
        f(jnp.ones(8)).block_until_ready()
    # one compile (the first call), inside warmup: no retrace
    assert reg._families["heatmap_compile_total"].labels(fn="f").value == 1
    assert tr.retraces_recent(600) == 0
    # a NEW SHAPE after warmup: a post-warmup retrace
    f(jnp.ones(16)).block_until_ready()
    assert reg._families["heatmap_compile_total"].labels(fn="f").value == 2
    assert (reg._families["heatmap_retrace_after_warmup_total"]
            .labels(fn="f").value == 1)
    assert tr.retraces_recent(600) == 1
    assert tr.retraces_recent(0) == 0  # outside a zero window
    snap = tr.snapshot()
    assert snap["functions"]["f"]["compiles"] == 2
    assert snap["functions"]["f"]["calls"] == 4
    assert snap["retraces_after_warmup"] == 1
    # compile seconds observed for both compiles
    assert reg._families["heatmap_compile_seconds"].labels(fn="f").count == 2


def test_compile_tracker_transparent_on_plain_callables():
    """A callable without a jit cache (host fallback paths) is passed
    through unharmed: no compiles recorded, results intact."""
    reg = Registry()
    tr = CompileTracker(reg, warmup=1)
    g = tr.wrap("g", lambda x: x * 2)
    assert g(21) == 42
    assert reg._families["heatmap_compile_total"].labels(fn="g").value == 0
    assert tr.retraces_recent(600) == 0


def test_memory_monitor_live_buffer_watermark():
    reg = Registry()
    mm = MemoryMonitor(reg)
    keep = jnp.ones((256, 256))  # noqa: F841 - held live across samples
    assert mm.sample()
    live = reg._families["heatmap_live_buffer_bytes"].value
    assert live >= keep.nbytes
    assert mm.watermark_bytes >= live
    # rate limit: an immediate re-sample inside the interval is skipped
    assert not mm.sample(min_interval_s=60.0)
    snap = mm.snapshot()
    assert snap["watermark_bytes"] == mm.watermark_bytes


def test_emit_ring_nbytes_accounting():
    from heatmap_tpu.engine.step import EmitRing

    ring = EmitRing(4)
    assert ring.nbytes == 0
    a = jnp.zeros((2, 17, 13), jnp.uint32)
    ring.append(a, 0)
    ring.append(jnp.ones((2, 17, 13), jnp.uint32), 1)
    assert ring.nbytes == 2 * a.nbytes
    ring.take()
    assert ring.nbytes == 0


def test_stack_sampler_aggregates_frames():
    s = StackSampler(hz=200.0)
    try:
        assert s.ensure_started()
        assert s.ensure_started()  # idempotent
        deadline = time.monotonic() + 5.0
        while s.snapshot(5)["samples"] < 5:
            assert time.monotonic() < deadline, "sampler produced nothing"
            time.sleep(0.02)
        snap = s.snapshot(5)
        assert snap["running"] and snap["frames"]
        top = snap["frames"][0]
        assert set(top) == {"thread", "frame", "count", "share"}
        assert s.tail(3) == s.snapshot(3)["frames"]
    finally:
        s.stop()
    assert not s.running


def test_stack_sampler_disabled_by_hz_zero(monkeypatch):
    monkeypatch.setenv("HEATMAP_STACKPROF_HZ", "0")
    s = StackSampler()
    assert not s.ensure_started() and not s.running
    monkeypatch.setenv("HEATMAP_STACKPROF_HZ", "nope")
    assert StackSampler().hz == 29.0  # garbage -> default


# ------------------------------------------------------------ runtime
def _mk_events(n, age_s=2):
    t0 = int(time.time()) - age_s
    return [{"provider": "p", "vehicleId": f"v{i % 7}",
             "lat": 42.0 + (i % 40) * 1e-3, "lon": -71.0,
             "speedKmh": 10.0, "ts": t0} for i in range(n)]


def _mk_runtime(tmp_path, **over):
    over.setdefault("checkpoint_dir", str(tmp_path / "ckpt"))
    over.setdefault("batch_size", 16)
    over.setdefault("state_capacity_log2", 8)
    over.setdefault("speed_hist_bins", 4)
    over.setdefault("store", "memory")
    over.setdefault("emit_flush_k", 1)
    over.setdefault("prefetch_batches", 0)
    cfg = load_config({}, **over)
    src = MemorySource(_mk_events(16 * 4))
    src.finish()
    return MicroBatchRuntime(cfg, src, MemoryStore(), checkpoint_every=0)


def _drain(rt):
    while rt.step_once():
        pass


def _force_retrace(rt):
    """Warm the fused step, then grow the slab: the next step's new
    shapes add a jit cache entry — a post-warmup retrace."""
    _drain(rt)
    assert rt.runtimeinfo.compile.retraces_recent(600) == 0
    rt._multi.grow(2 * rt._multi.capacity_per_shard)
    src2 = MemorySource(_mk_events(16 * 2))
    src2.finish()
    rt.source = src2
    _drain(rt)


def test_acceptance_post_warmup_retrace(tmp_path, monkeypatch):
    """Forced retrace -> visible metric + /healthz degradation + an
    automatic ENRICHED flight-recorder dump."""
    monkeypatch.setenv("HEATMAP_SLO_FRESHNESS_P50_MS", "1e9")  # isolate
    frdir = tmp_path / "fr"
    rt = _mk_runtime(tmp_path, flightrec_dir=str(frdir))
    try:
        _force_retrace(rt)
        # (a) the metric
        fam = rt.metrics.registry._families[
            "heatmap_retrace_after_warmup_total"]
        assert sum(c.value for c in fam.children.values()) >= 1
        # (b) /healthz degrades on the retrace check
        from heatmap_tpu.serve.api import healthz_payload

        payload, down = healthz_payload(rt)
        assert not down and payload["status"] == "degraded"
        chk = payload["checks"]["retrace_after_warmup"]
        assert chk["value"] >= 1 and not chk["ok"]
        # (c) the watchdog auto-captures an enriched dump
        path = rt.slo_watchdog.check_once()
        assert path is not None
        d = json.loads(open(path).read())
        assert d["reason"].startswith("slo degraded:")
        assert "retrace_after_warmup" in d["reason"]
        fns = d["runtimeinfo"]["compile"]["functions"]
        assert any(f["compiles"] >= 2 for f in fns.values())
        assert d["runtimeinfo"]["compile"]["retraces_after_warmup"] >= 1
        assert d["runtimeinfo"]["memory"]["watermark_bytes"] > 0
        assert isinstance(d["stacks"], list)
        assert not d["healthz"]["checks"]["retrace_after_warmup"]["ok"]
    finally:
        rt.close()


def test_acceptance_memory_watermark_breach(tmp_path, monkeypatch):
    """Forced watermark breach (1-byte budget) -> visible metric +
    /healthz degradation + automatic enriched dump."""
    monkeypatch.setenv("HEATMAP_SLO_FRESHNESS_P50_MS", "1e9")
    monkeypatch.setenv("HEATMAP_SLO_MEM_BYTES", "1")
    frdir = tmp_path / "fr"
    rt = _mk_runtime(tmp_path, flightrec_dir=str(frdir))
    try:
        _drain(rt)  # the loop samples memory at 1 Hz -> watermark set
        # (a) the metric
        wm = rt.metrics.registry._families[
            "heatmap_live_buffer_watermark_bytes"].value
        assert wm > 1
        # (b) /healthz
        from heatmap_tpu.serve.api import healthz_payload

        payload, down = healthz_payload(rt)
        assert payload["status"] == "degraded"
        chk = payload["checks"]["memory_watermark_bytes"]
        assert chk["value"] > chk["budget"] and not chk["ok"]
        # (c) the enriched auto-capture
        path = rt.slo_watchdog.check_once()
        assert path is not None
        d = json.loads(open(path).read())
        assert "memory_watermark_bytes" in d["reason"]
        assert d["runtimeinfo"]["memory"]["watermark_bytes"] > 1
    finally:
        rt.close()


def test_healthz_checks_quiet_when_healthy(tmp_path, monkeypatch):
    """No retraces, no memory budget: the introspection checks stay out
    of the payload entirely (no noise on a healthy pipeline)."""
    monkeypatch.delenv("HEATMAP_SLO_MEM_BYTES", raising=False)
    monkeypatch.delenv("HEATMAP_SLO_RETRACES", raising=False)
    rt = _mk_runtime(tmp_path)
    try:
        _drain(rt)
        checks, degraded = healthz_checks(rt)
        assert checks == {} and not degraded
    finally:
        rt.close()
    # and on a runtime-less object (serve-only healthz path)
    assert healthz_checks(object()) == ({}, False)


def test_watchdog_one_capture_per_episode(tmp_path, monkeypatch):
    """While the verdict STAYS degraded no second dump fires; a recovery
    re-arms the watchdog for the next episode."""
    monkeypatch.setenv("HEATMAP_SLO_FRESHNESS_P50_MS", "1e9")
    frdir = tmp_path / "fr"
    rt = _mk_runtime(tmp_path, flightrec_dir=str(frdir))
    try:
        _drain(rt)
        wd = SloWatchdog(rt, interval_s=0, cooldown_s=0)
        monkeypatch.setenv("HEATMAP_SLO_MEM_BYTES", "1")  # degraded
        p1 = wd.check_once()
        assert p1 is not None
        assert wd.check_once() is None        # same episode: no dump
        monkeypatch.setenv("HEATMAP_SLO_MEM_BYTES", "1e18")  # recovered
        assert wd.check_once() is None        # transition to ok
        monkeypatch.setenv("HEATMAP_SLO_MEM_BYTES", "1")  # episode 2
        p2 = wd.check_once()
        assert p2 is not None and p2 != p1
        assert wd.n_captures == 2
    finally:
        rt.close()


def test_watchdog_thread_fires_on_degradation(tmp_path, monkeypatch):
    monkeypatch.setenv("HEATMAP_SLO_FRESHNESS_P50_MS", "1e9")
    monkeypatch.setenv("HEATMAP_SLO_MEM_BYTES", "1")
    frdir = tmp_path / "fr"
    rt = _mk_runtime(tmp_path, flightrec_dir=str(frdir))
    try:
        _drain(rt)
        wd = SloWatchdog(rt, interval_s=0.05, cooldown_s=0)
        assert wd.start()
        deadline = time.monotonic() + 5.0
        while wd.n_captures == 0 and time.monotonic() < deadline:
            time.sleep(0.02)
        wd.stop()
        assert wd.n_captures >= 1
        assert list(frdir.glob("flightrec-*.json"))
    finally:
        rt.close()


def test_crash_dump_carries_runtime_introspection(tmp_path):
    """Satellite: the CRASH-path flight record is enriched too — the
    runtimeinfo snapshot and the stack tail ride every dump."""
    from heatmap_tpu.testing.faults import CrashingSource, InjectedCrash

    frdir = tmp_path / "fr"
    cfg = load_config({}, checkpoint_dir=str(tmp_path / "ckpt"),
                      batch_size=16, state_capacity_log2=8,
                      speed_hist_bins=4, store="memory", emit_flush_k=1,
                      prefetch_batches=0, flightrec_dir=str(frdir))
    src = CrashingSource(MemorySource(_mk_events(48)),
                         crash_after_polls=2)
    rt = MicroBatchRuntime(cfg, src, MemoryStore(), checkpoint_every=0)
    with pytest.raises(InjectedCrash):
        rt.run()
    files = sorted(frdir.glob("flightrec-*.json"))
    assert len(files) == 1
    d = json.loads(files[0].read_text())
    ri = d["runtimeinfo"]
    assert ri["compile"]["functions"]  # the wrapped entry points
    assert any(f["compiles"] >= 1 for f in ri["compile"]["functions"].values())
    assert ri["memory"]["watermark_bytes"] > 0
    assert isinstance(d["stacks"], list)


def test_metrics_exposition_carries_new_families(tmp_path):
    rt = _mk_runtime(tmp_path)
    try:
        _drain(rt)
        txt = rt.metrics.expose_text()
        for fam in ("heatmap_compile_total",
                    "heatmap_compile_seconds",
                    "heatmap_retrace_after_warmup_total",
                    "heatmap_live_buffer_bytes",
                    "heatmap_live_buffer_watermark_bytes",
                    "heatmap_emit_ring_slab_bytes",
                    "heatmap_device_hbm_watermark_bytes"):
            assert f"# TYPE {fam}" in txt, fam
        assert 'heatmap_compile_total{fn="multi_step' in txt
    finally:
        rt.close()


def test_introspection_bundle_snapshot_shape():
    reg = Registry()
    ri = RuntimeIntrospection(reg, ring_bytes_fn=lambda: 123)
    snap = ri.snapshot()
    assert set(snap) == {"compile", "memory"}
    assert reg._families["heatmap_emit_ring_slab_bytes"].value == 123


def test_watchdog_episode_survives_cooldown_window(tmp_path, monkeypatch):
    """A degradation that BEGINS inside the cooldown window must still
    be captured once the cooldown lapses — the transition is only
    consumed by a successful dump, never by a blocked tick."""
    monkeypatch.setenv("HEATMAP_SLO_FRESHNESS_P50_MS", "1e9")
    rt = _mk_runtime(tmp_path, flightrec_dir=str(tmp_path / "fr"))
    try:
        _drain(rt)
        wd = SloWatchdog(rt, interval_s=0, cooldown_s=0)
        monkeypatch.setenv("HEATMAP_SLO_MEM_BYTES", "1")
        assert wd.check_once() is not None           # episode 1
        monkeypatch.setenv("HEATMAP_SLO_MEM_BYTES", "1e18")
        assert wd.check_once() is None               # recovered
        wd.cooldown_s = 3600
        monkeypatch.setenv("HEATMAP_SLO_MEM_BYTES", "1")
        assert wd.check_once() is None  # episode 2, inside cooldown
        assert wd.check_once() is None  # still blocked, NOT consumed
        wd.cooldown_s = 0               # cooldown lapses mid-episode
        assert wd.check_once() is not None  # episode 2 captured late
        assert wd.n_captures == 2
    finally:
        rt.close()
