"""Kafka wire stack: record batch codec, CRC32C/murmur2 goldens, the
cluster client against the in-process mock broker, and the KafkaSource /
KafkaPublisher round trip over real sockets."""

import json

import pytest

from heatmap_tpu.kafka import KafkaClient, KafkaError, Record, decode_batches, encode_batch
from heatmap_tpu.kafka.client import EARLIEST, LATEST, murmur2, partition_for_key
from heatmap_tpu.kafka.records import crc32c
from heatmap_tpu.testing.mock_kafka import MockKafkaBroker


# ---- codecs ----------------------------------------------------------------

def test_crc32c_goldens():
    # RFC 3720 test vectors
    assert crc32c(b"") == 0
    assert crc32c(b"123456789") == 0xE3069283
    assert crc32c(bytes(32)) == 0x8A9136AA


def test_murmur2_properties():
    # deterministic, 32-bit, sensitive to every byte position
    a = murmur2(b"veh-1")
    assert 0 <= a < 1 << 32
    assert murmur2(b"veh-1") == a
    assert murmur2(b"veh-2") != a
    assert murmur2(b"veh-1 ") != a
    # regression pins for the implementation (algorithm: murmur2-32,
    # seed 0x9747b28c, little-endian 4-byte blocks — the Kafka default)
    assert partition_for_key(b"veh-1", 3) in range(3)
    hits = {partition_for_key(f"veh-{i}".encode(), 3) for i in range(100)}
    assert hits == {0, 1, 2}
    for n in (1, 2, 3, 4, 5, 8, 9):  # tail-length cases
        assert 0 <= murmur2(bytes(range(n))) < 1 << 32


def test_record_batch_roundtrip():
    recs = [
        Record(0, 1_700_000_000_000, b"veh-1", b'{"lat": 1}'),
        Record(1, 1_700_000_000_500, None, b'{"lat": 2}',
               headers=[("h", b"v"), ("empty", b"")]),
        Record(2, 1_700_000_001_000, b"veh-2", None),
    ]
    blob = encode_batch(recs, base_offset=41)
    out = decode_batches(blob)
    assert [r.offset for r in out] == [41, 42, 43]
    assert [r.timestamp_ms for r in out] == [r.timestamp_ms for r in recs]
    assert out[0].key == b"veh-1" and out[0].value == b'{"lat": 1}'
    assert out[1].key is None and out[1].headers == [("h", b"v"), ("empty", b"")]
    assert out[2].value is None


def test_record_batch_crc_and_truncation():
    blob = encode_batch([Record(0, 0, b"k", b"v")])
    corrupted = blob[:25] + bytes([blob[25] ^ 0xFF]) + blob[26:]
    with pytest.raises(ValueError, match="CRC"):
        decode_batches(corrupted)
    # truncated tail batch is skipped, not an error (broker semantics)
    two = blob + blob
    assert len(decode_batches(two[:-10])) == 1
    assert len(decode_batches(two)) == 2


def test_tolerant_decode_skips_poisoned_batch():
    from heatmap_tpu.kafka import decode_batches_tolerant

    good1 = encode_batch([Record(0, 0, b"a", b"one"),
                          Record(0, 1, b"b", b"two")], base_offset=0)
    bad = bytearray(encode_batch([Record(0, 2, b"c", b"POISON")],
                                 base_offset=2))
    bad[-2] ^= 0xFF  # corrupt a record payload byte: CRC mismatch
    good2 = encode_batch([Record(0, 3, b"d", b"three")], base_offset=3)
    recs, next_off, skipped = decode_batches_tolerant(
        bytes(good1) + bytes(bad) + good2, 0)
    assert [r.value for r in recs] == [b"one", b"two", b"three"]
    assert skipped == 1
    assert next_off == 4  # advanced past the poisoned batch


# ---- client against mock broker --------------------------------------------

@pytest.fixture()
def broker():
    b = MockKafkaBroker()
    yield b
    b.close()


def test_produce_fetch_roundtrip(broker):
    c = KafkaClient(broker.bootstrap)
    assert c.partitions("t1") == [0, 1, 2]
    base = c.produce("t1", 0, [Record(0, 1000, b"a", b"one"),
                               Record(0, 1001, b"b", b"two")])
    assert base == 0
    base = c.produce("t1", 0, [Record(0, 1002, b"c", b"three")])
    assert base == 2
    fr = c.fetch("t1", 0, 0)
    assert fr.high_watermark == 3 and fr.next_offset == 3
    assert [r.value for r in fr.records] == [b"one", b"two", b"three"]
    assert [r.offset for r in fr.records] == [0, 1, 2]
    # fetch from mid-offset
    fr = c.fetch("t1", 0, 2)
    assert [r.value for r in fr.records] == [b"three"]
    c.close()


def test_list_offsets_latest_earliest(broker):
    c = KafkaClient(broker.bootstrap)
    c.produce("t2", 1, [Record(0, 0, None, b"x")])
    assert c.list_offsets("t2", EARLIEST) == {0: 0, 1: 0, 2: 0}
    assert c.list_offsets("t2", LATEST) == {0: 0, 1: 1, 2: 0}
    c.close()


def test_fetch_offset_out_of_range(broker):
    c = KafkaClient(broker.bootstrap)
    c.partitions("t3")
    with pytest.raises(KafkaError, match="OFFSET_OUT_OF_RANGE"):
        c.fetch("t3", 0, 99)
    c.close()


# ---- source + publisher over the wire --------------------------------------

def _events(n, start=0):
    return [{"provider": "mbta", "vehicleId": f"veh-{i % 7}",
             "lat": 42.3 + i * 1e-4, "lon": -71.05, "speedKmh": 30.0,
             "bearing": 0.0, "accuracyM": 5.0,
             "ts": 1_700_000_000 + start + i} for i in range(n)]


def _drain(src, want: int, polls: int = 10) -> tuple[int, set]:
    """Poll until `want` events arrive; returns (count, ts set).  Sources
    may return dict lists or columnar EventColumns (native decode path)."""
    from heatmap_tpu.stream.events import EventColumns

    n, ts = 0, set()
    for _ in range(polls):
        polled = src.poll(64)
        if isinstance(polled, EventColumns):
            n += len(polled)
            ts.update(int(t) for t in polled.ts_s)
        else:
            n += len(polled)
            ts.update(e["ts"] for e in polled)
        if n >= want:
            break
    return n, ts


def test_publisher_source_roundtrip(broker):
    from heatmap_tpu.producers.base import KafkaPublisher
    from heatmap_tpu.stream.source import KafkaSource

    src = KafkaSource(broker.bootstrap, "mobility.positions.v1")  # at LATEST
    pub = KafkaPublisher(broker.bootstrap, "mobility.positions.v1")
    sent = _events(50)
    pub.publish(sent)
    pub.flush()
    n, ts = _drain(src, 50)
    assert n == 50
    # same canonical events; keying spread them across partitions
    assert ts == {e["ts"] for e in sent}
    offs = src.offset()
    assert sum(offs.values()) == 50 and len(offs) == 3

    # checkpoint resume: a new consumer seeked to the saved offsets sees
    # only post-checkpoint events (replay-exactness, SURVEY.md §5.4)
    pub.publish(_events(5, start=1000))
    pub.flush()
    src2 = KafkaSource(broker.bootstrap, "mobility.positions.v1")
    src2.seek(offs)
    n2, ts2 = _drain(src2, 5)
    assert ts2 == {e["ts"] for e in _events(5, start=1000)}
    pub.close()
    src.close()
    src2.close()


def test_publisher_retains_pending_on_error(broker, monkeypatch):
    from heatmap_tpu.producers.base import KafkaPublisher

    pub = KafkaPublisher(broker.bootstrap, "t4")
    pub.publish(_events(3))

    def boom(*a, **kw):
        raise ConnectionError("broker gone")

    monkeypatch.setattr(pub._p, "produce", boom)
    with pytest.raises(ConnectionError):
        pub.flush()
    # undelivered events stay queued for the poll loop's backoff+retry
    assert sum(len(v) for v in pub._pending.values()) == 3
    monkeypatch.undo()
    pub.flush()
    assert sum(len(v) for v in pub._pending.values()) == 0
    c = KafkaClient(broker.bootstrap)
    assert sum(c.list_offsets("t4", LATEST).values()) == 3
    c.close()
    pub.close()


def test_partial_take_exactly_once(broker):
    """max_events smaller than a partition's backlog forces the columnar
    path's partial-take branch (blob cut at val_pos, offset rewound to the
    last taken value): tiny polls must still deliver every event exactly
    once, including across a checkpoint/seek boundary."""
    from heatmap_tpu.producers.base import KafkaPublisher
    from heatmap_tpu.stream.events import EventColumns
    from heatmap_tpu.stream.source import KafkaSource

    src = KafkaSource(broker.bootstrap, "t5")
    pub = KafkaPublisher(broker.bootstrap, "t5")
    sent = _events(60)
    pub.publish(sent)
    pub.flush()

    def take(s, n):
        polled = s.poll(n)
        if isinstance(polled, EventColumns):
            assert len(polled) <= n
            return [int(t) for t in polled.ts_s]
        assert len(polled) <= n
        return [e["ts"] for e in polled]

    seen = []
    for _ in range(10):
        seen.extend(take(src, 7))  # 60 events / 3 partitions >> 7
        if len(seen) >= 21:
            break
    mid_offsets = src.offset()

    # resume from the checkpointed offsets on a fresh consumer
    src2 = KafkaSource(broker.bootstrap, "t5")
    src2.seek(mid_offsets)
    for _ in range(40):
        seen.extend(take(src2, 7))
        if len(seen) >= 60:
            break
    assert sorted(seen) == sorted(e["ts"] for e in sent)  # exactly once
    pub.close()
    src.close()
    src2.close()


def test_partial_take_resumes_at_first_untaken(broker):
    """A partial take must resume at the FIRST untaken value's offset, so
    tombstones (and skipped batches) between the last taken and the first
    untaken value are not re-fetched on every subsequent poll."""
    from heatmap_tpu.native import maybe_decoder
    from heatmap_tpu.stream.events import EventColumns
    from heatmap_tpu.stream.source import KafkaSource

    if maybe_decoder() is None:
        pytest.skip("columnar path needs the C++ decoder")
    c = KafkaClient(broker.bootstrap)
    vals = [json.dumps(e).encode() for e in _events(3)]
    c.produce("t6", 0, [Record(0, 0, b"k", vals[0]),
                        Record(0, 0, b"k", None),  # tombstone in the gap
                        Record(0, 0, b"k", vals[1]),
                        Record(0, 0, b"k", vals[2])])
    src = KafkaSource(broker.bootstrap, "t6")
    src.seek({0: 0, 1: 0, 2: 0})
    polled = src.poll(1)
    assert isinstance(polled, EventColumns) and len(polled) == 1
    # first untaken value sits at kafka offset 2, past the tombstone at 1
    assert src.offset()[0] == 2
    rest = src.poll(16)
    assert len(rest) == 2
    assert sorted([int(t) for t in polled.ts_s] +
                  [int(t) for t in rest.ts_s]) == [e["ts"] for e in _events(3)]
    src.close()
    c.close()


def test_consumer_survives_broker_outage_and_truncation():
    """A broker outage must not raise out of poll(); when a broker comes
    back on the same port with an empty log (retention truncation from
    the consumer's point of view), the consumer resets to earliest and
    streams the new data."""
    from heatmap_tpu.producers.base import KafkaPublisher
    from heatmap_tpu.stream.events import EventColumns
    from heatmap_tpu.stream.source import KafkaSource

    def drain_n(src, n, polls=12):
        got = []
        for _ in range(polls):
            polled = src.poll(64)
            if isinstance(polled, EventColumns):
                got.extend(int(t) for t in polled.ts_s)
            else:
                got.extend(e["ts"] for e in polled or [])
            if len(got) >= n:
                break
        return got

    b1 = MockKafkaBroker()
    host, port = b1.bootstrap.split(":")
    src = KafkaSource(b1.bootstrap, "tout")
    pub = KafkaPublisher(b1.bootstrap, "tout")
    pub.publish(_events(60))  # ~20 records per partition
    pub.flush()
    assert sorted(drain_n(src, 60)) == [e["ts"] for e in _events(60)]
    pub.close()
    b1.close()

    # outage: polls must degrade to warnings + empty results, not raise
    for _ in range(3):
        polled = src.poll(64)
        assert polled == [] or len(polled) == 0

    # "restarted" broker, same port, with a log SHORTER than the consumer's
    # offsets on every partition (what retention truncation looks like):
    # OFFSET_OUT_OF_RANGE -> reset to earliest -> stream the new data
    b2 = MockKafkaBroker(host=host, port=int(port))
    try:
        pub2 = KafkaPublisher(b2.bootstrap, "tout")
        newer = _events(6, start=1000)
        pub2.publish(newer)
        pub2.flush()
        got = drain_n(src, 6, polls=20)
        assert sorted(got) == [e["ts"] for e in newer]
        pub2.close()
        src.close()
    finally:
        b2.close()

def test_kip896_broker_accepted():
    """Kafka 4.x (KIP-896) removed early protocol versions; the mock's
    4.x table raises the minima ABOVE the historical floor pins
    (Metadata>=4, ListOffsets>=2).  The client must NEGOTIATE the higher
    versions per connection and round-trip end to end — this is the
    README supported-broker-range claim (a hard-pinned client would be
    rejected at connect here)."""
    from heatmap_tpu.kafka.protocol import (
        API_FETCH, API_LIST_OFFSETS, API_METADATA, API_PRODUCE,
    )
    from heatmap_tpu.testing.mock_kafka import API_VERSIONS_KIP896

    with MockKafkaBroker(api_versions=API_VERSIONS_KIP896) as bootstrap:
        c = KafkaClient(bootstrap)
        assert c.partitions("t896") == [0, 1, 2]
        base = c.produce("t896", 0, [Record(0, 1000, b"k", b"v"),
                                     Record(0, 1001, b"k2", b"w")])
        assert base == 0
        fr = c.fetch("t896", 0, 0)
        assert [r.value for r in fr.records] == [b"v", b"w"]
        assert c.list_offsets("t896")[0] == 2
        # the negotiated versions are the intersection maxima, not pins
        conn = next(iter(c._conns.values()))
        assert conn._use[API_PRODUCE] == 7
        assert conn._use[API_FETCH] == 11
        assert conn._use[API_LIST_OFFSETS] == 3
        assert conn._use[API_METADATA] == 7
        c.close()


def test_legacy_broker_negotiates_implemented_maxima():
    """Against a 2.x-era table the client picks min(impl_max, broker_max)
    per API — e.g. Metadata 7 (impl) vs broker 8 -> 7; Fetch 11 vs 11."""
    from heatmap_tpu.kafka.protocol import (
        API_FETCH, API_LIST_OFFSETS, API_METADATA, API_PRODUCE,
    )

    with MockKafkaBroker() as bootstrap:
        c = KafkaClient(bootstrap)
        c.produce("tleg", 0, [Record(0, 1000, b"k", b"v")])
        assert [r.value for r in c.fetch("tleg", 0, 0).records] == [b"v"]
        conn = next(iter(c._conns.values()))
        assert conn._use[API_PRODUCE] == 7      # min(7, 8)
        assert conn._use[API_FETCH] == 11       # min(11, 11)
        assert conn._use[API_LIST_OFFSETS] == 3  # min(3, 5)
        assert conn._use[API_METADATA] == 7     # min(7, 8)
        c.close()


def test_dropped_pin_fails_actionably():
    """A future broker that drops the pinned versions must fail AT
    CONNECT with the API name, the broker's served range, and a remedy —
    not deep in a produce call with a raw protocol error."""
    from heatmap_tpu.kafka.protocol import (
        API_FETCH, API_LIST_OFFSETS, API_METADATA, API_PRODUCE,
        API_VERSIONS,
    )

    future = ((API_PRODUCE, 12, 15), (API_FETCH, 17, 20),
              (API_LIST_OFFSETS, 10, 12), (API_METADATA, 13, 15),
              (API_VERSIONS, 0, 5))
    with MockKafkaBroker(api_versions=future) as bootstrap:
        with pytest.raises(KafkaError) as ei:
            KafkaClient(bootstrap)
        msg = str(ei.value)
        assert "Produce" in msg and "v12..v15" in msg and "v3..v7" in msg
        assert "HEATMAP_KAFKA_IMPL" in msg


def test_poll_sweeps_until_filled(broker, monkeypatch):
    """A poll larger than one fetch's ~1 MiB worth of records must keep
    sweeping the partitions until it fills (a single round-robin pass
    used to cap a poll at ~n_partitions MiB, forcing the runtime into
    partial-batch carries), while the sweep loop stays bounded by
    sweep_budget_s for live tails."""
    import numpy as np

    from heatmap_tpu.producers.base import KafkaPublisher
    from heatmap_tpu.stream.events import columns_from_arrays
    from heatmap_tpu.stream.source import KafkaSource

    monkeypatch.setenv("HEATMAP_EVENT_FORMAT", "columnar")
    monkeypatch.setenv("HEATMAP_KAFKA_IMPL", "wire")
    src = KafkaSource(broker.bootstrap, "sweep.topic")  # at LATEST
    pub = KafkaPublisher(broker.bootstrap, "sweep.topic",
                         event_format="columnar")
    n = 1 << 17  # ~3.4 MiB of columnar records — >3 fetches worth
    cols = columns_from_arrays(
        np.full(n, 42.3, np.float32), np.full(n, -71.05, np.float32),
        np.full(n, 30.0, np.float32),
        np.full(n, 1_700_000_000, np.int32),
        provider_id=np.zeros(n, np.int32),
        vehicle_id=(np.arange(n) % 50).astype(np.int32),
        providers=["p"], vehicles=[f"v{i}" for i in range(50)])
    assert pub.publish_columns(cols) == n
    pub.flush()
    polled = src.poll(n)
    assert len(polled) >= n  # ONE poll call filled the whole request
    src.close()
    pub.close()
