"""Differential tests for the C++ positions pipeline-op encoder
(native/positions_ops.cpp) against the Python builder
(sink.mongo._monotonic_update_pipeline + PositionDoc), plus monotonic
semantics end-to-end over the wire against the mock mongod."""

import numpy as np
import pytest

from heatmap_tpu.native import NativePositionOps
from heatmap_tpu.sink import bson
from heatmap_tpu.sink.base import PositionDoc, PositionRows, epoch_to_dt
from heatmap_tpu.sink.mongo import _monotonic_update_pipeline

pytestmark = pytest.mark.skipif(
    not NativePositionOps.available(), reason="no C++ toolchain")


def make_rows(rng, n):
    return PositionRows(
        lat=rng.uniform(-90, 90, n).astype(np.float32),
        lon=rng.uniform(-180, 180, n).astype(np.float32),
        ts_ms=(1_700_000_000_000 + rng.integers(0, 10**6, n)).astype(np.int64),
        providers=[["mbta", "opensky", "tëst-ünïcode"][i % 3]
                   for i in range(n)],
        vehicles=[f"veh-{i}" for i in range(n)],
    )


def python_updates(rows: PositionRows) -> list[dict]:
    out = []
    for d in rows.to_docs():
        out.append({"q": {"_id": d["_id"]},
                    "u": _monotonic_update_pipeline(d),
                    "upsert": True})
    return out


def test_native_matches_python(rng):
    enc = NativePositionOps()
    rows = make_rows(rng, 97)
    ops, offsets, n = enc.encode(rows)
    want = python_updates(rows)
    assert n == len(want) == 97
    start = 0
    for w, end in zip(want, offsets):
        got = bson.decode(ops[start:int(end)])
        start = int(end)
        assert list(got) == ["q", "u", "upsert"]
        assert got["q"] == w["q"]
        assert got["upsert"] is True
        # the pipeline decodes back to the exact same nested structure
        assert got["u"] == w["u"], got["u"]
    assert start == len(ops)


def test_empty_rows():
    enc = NativePositionOps()
    rows = PositionRows(np.zeros(0, np.float32), np.zeros(0, np.float32),
                        np.zeros(0, np.int64), [], [])
    ops, offsets, n = enc.encode(rows)
    assert n == 0 and ops == b"" and len(offsets) == 0


def test_monotonic_semantics_over_wire(rng):
    """Native packed path vs Python docs path against two mock servers:
    same final state, and stale updates are no-ops on both."""
    from heatmap_tpu.sink.mongo import MongoStore, _WireBackend
    from heatmap_tpu.testing.mock_mongod import MockMongod

    rows = make_rows(rng, 40)
    older = rows._replace(
        ts_ms=rows.ts_ms - 5000,
        lat=rows.lat + 1.0,
    )
    newer = rows._replace(ts_ms=rows.ts_ms + 5000)

    with MockMongod() as uri_a, MockMongod() as uri_b:
        sa = MongoStore(uri_a, "mobility", ensure_indexes=False,
                        backend=_WireBackend(uri_a, "mobility"))
        sb = MongoStore(uri_b, "mobility", ensure_indexes=False,
                        backend=_WireBackend(uri_b, "mobility"))
        n1 = sa.upsert_positions_packed(rows)
        assert sa._pos_ops is not None, "native path must engage"
        assert n1 == 40  # all inserts apply
        sb.upsert_positions(rows.to_docs())

        # stale rows: matched but unmodified on both paths
        assert sa.upsert_positions_packed(older) == 0
        assert sb.upsert_positions(older.to_docs()) == 0

        # newer rows: applied on both paths
        assert sa.upsert_positions_packed(newer) == 40
        assert sb.upsert_positions(newer.to_docs()) == 40

        a = sorted(sa.all_positions(), key=lambda d: d["_id"])
        b = sorted(sb.all_positions(), key=lambda d: d["_id"])
        assert a == b
        want_ts = {f"{p}|{v}": epoch_to_dt(int(t) / 1000.0)
                   for p, v, t in zip(newer.providers, newer.vehicles,
                                      newer.ts_ms)}
        assert all(d["ts"] == want_ts[d["_id"]] for d in a)
        sa.close()
        sb.close()


def test_undersized_buffer_resizes_and_retries(rng, monkeypatch):
    """When the conservative _DOC_BOUND estimate is exceeded, encode must
    reallocate to the exact size the C side reports and retry, not raise
    (mirrors NativeTileOps.encode)."""
    rows = make_rows(rng, 23)
    ops, offsets, n = NativePositionOps().encode(rows)
    monkeypatch.setattr(NativePositionOps, "_DOC_BOUND", 0)
    ops2, offsets2, n2 = NativePositionOps().encode(rows)
    assert n2 == n == 23
    assert ops2 == ops
    np.testing.assert_array_equal(offsets2, offsets)
