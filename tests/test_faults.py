"""Resilience: crash-restart replay equivalence, transient-sink retry,
permanent-sink poisoning, and the profiler trace hook (SURVEY.md §5.1/5.3:
the reference has neither fault injection nor profiling)."""

import glob

import numpy as np
import pytest

from heatmap_tpu.config import load_config
from heatmap_tpu.sink import AsyncWriter, MemoryStore
from heatmap_tpu.stream import MicroBatchRuntime, SyntheticSource
from heatmap_tpu.testing.faults import (
    BrokenStore, CrashingSource, FlakyStore, InjectedCrash,
)

N_EVENTS = 4096
BATCH = 512


def mk_cfg(tmp_path, **kw):
    kw.setdefault("batch_size", BATCH)
    kw.setdefault("checkpoint_dir", str(tmp_path / "ckpt"))
    kw.setdefault("store", "memory")
    return load_config({}, **kw)


def mk_src():
    return SyntheticSource(n_events=N_EVENTS, n_vehicles=64,
                           events_per_second=BATCH)


def tiles_snapshot(store):
    return {d["_id"]: (d["count"], round(d["avgSpeedKmh"], 4))
            for d in store._tiles.values()}


def reference_run(tmp_path):
    cfg = mk_cfg(tmp_path, checkpoint_dir=str(tmp_path / "ckpt-ref"))
    store = MemoryStore()
    rt = MicroBatchRuntime(cfg, mk_src(), store, checkpoint_every=0)
    rt.run()
    return tiles_snapshot(store)


@pytest.mark.parametrize("crash_after", [1, 3, 6])
def test_crash_restart_replay_equivalence(tmp_path, crash_after):
    """Kill the job mid-stream at several points; a resumed runtime must
    converge the store to exactly the uncrashed run's tiles."""
    expected = reference_run(tmp_path)

    cfg = mk_cfg(tmp_path)
    store = MemoryStore()
    src = CrashingSource(mk_src(), crash_after_polls=crash_after)
    rt = MicroBatchRuntime(cfg, src, store, checkpoint_every=1)
    with pytest.raises(InjectedCrash):
        rt.run()

    # process restart: fresh runtime, same checkpoint dir + store
    rt2 = MicroBatchRuntime(cfg, mk_src(), store, checkpoint_every=1)
    rt2.run()
    assert tiles_snapshot(store) == expected


def test_crash_during_sink_flush_replays_idempotently(tmp_path):
    """Crash after some writes landed but before the checkpoint commits:
    replay re-applies the same docs; idempotent upserts converge."""
    expected = reference_run(tmp_path)

    cfg = mk_cfg(tmp_path)
    store = MemoryStore()
    # checkpoint_every=4 → hard death at poll 6 leaves 2 batches written
    # to the store but NOT covered by the checkpoint → they replay on
    # resume.  Manual stepping (no close()) models a process killed before
    # any shutdown checkpoint could run.
    src = CrashingSource(mk_src(), crash_after_polls=6)
    rt = MicroBatchRuntime(cfg, src, store, checkpoint_every=4)
    with pytest.raises(InjectedCrash):
        while rt.step_once():
            pass
    rt.writer.drain()  # the in-flight writes had landed before the death
    rt._ckpt_join()    # ...and so had the (async) epoch-4 commit

    rt2 = MicroBatchRuntime(cfg, mk_src(), store, checkpoint_every=4)
    assert rt2.epoch == 4  # resumed at the last committed checkpoint
    rt2.run()
    assert tiles_snapshot(store) == expected


def test_transient_sink_faults_absorbed_by_retry(tmp_path):
    """A flaky store (transient failures) must not lose data or kill the
    job: AsyncWriter retries with backoff."""
    expected = reference_run(tmp_path)

    cfg = mk_cfg(tmp_path)
    flaky = FlakyStore(MemoryStore(), fail_rate=0.4, seed=7)
    rt = MicroBatchRuntime(cfg, mk_src(), flaky, checkpoint_every=2)
    rt.writer.backoff_s = 0.01  # keep the test fast
    rt.run()
    assert flaky.injected > 0, "schedule never fired; test is vacuous"
    assert tiles_snapshot(flaky.inner) == expected
    assert rt.writer.counters["sink_retries"] == flaky.injected


def test_permanent_sink_failure_poisons_and_blocks_checkpoint():
    w = AsyncWriter(BrokenStore(), retries=1, backoff_s=0.01)
    w.submit_tiles([{"_id": "x"}])
    with pytest.raises(RuntimeError):
        w.drain()
    assert w.poisoned
    with pytest.raises(RuntimeError):
        w.submit_tiles([{"_id": "y"}])


@pytest.mark.slow  # tier-1 budget: see pyproject markers
def test_profiler_trace_capture(tmp_path, monkeypatch):
    """HEATMAP_PROFILE_DIR captures a device trace over the hot loop."""
    trace_dir = tmp_path / "trace"
    monkeypatch.setenv("HEATMAP_PROFILE_DIR", str(trace_dir))
    monkeypatch.setenv("HEATMAP_PROFILE_SKIP", "1")
    monkeypatch.setenv("HEATMAP_PROFILE_BATCHES", "2")
    cfg = mk_cfg(tmp_path)
    store = MemoryStore()
    rt = MicroBatchRuntime(cfg, mk_src(), store, checkpoint_every=0)
    rt.run()
    produced = glob.glob(str(trace_dir / "**" / "*"), recursive=True)
    assert produced, "no trace files written"
