"""Tier-1 guard: a broken native C++ build FAILS the suite.

The native library builds lazily and, on any compile error, silently
degrades to the Python fallbacks — right for production resilience,
wrong for CI: a broken .cpp would quietly disable the decoder/tile-ops/
kafka-codec/h3-snap fast paths AND skip every test gated on
``native available()``.  tools/check_native_build.py forces a real
compile + load + symbol bind; running it here (tier-1, not slow) turns
the silent fallback into a red suite.  ~17 s on this host — inside the
tier-1 budget.  A host without a C++ toolchain is an environment
property, not a regression: the tool exits 0 with a SKIP line there.
"""

import os
import subprocess
import sys

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), os.pardir))


def test_native_build_compiles_and_loads():
    tool = os.path.join(REPO, "tools", "check_native_build.py")
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    p = subprocess.run([sys.executable, tool], capture_output=True,
                       text=True, timeout=280, env=env, cwd=REPO)
    assert p.returncode == 0, (
        f"native build check failed:\n{p.stdout}\n{p.stderr[-8000:]}")
    assert "OK:" in p.stdout or "SKIP:" in p.stdout, p.stdout
