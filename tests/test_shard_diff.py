"""Sharded runtime differential: folding the same event corpus through
1 shard vs N H3-partitioned shards must produce BYTE-IDENTICAL merged
emits — including invalid, late, and duplicate events (ISSUE 7
acceptance, the same discipline PR 2 pinned the columnar path with).

Why this holds by construction (and what these tests keep honest):

- the ownership filter preserves row order and compacts owned rows to
  the batch prefix, so each (cell, window) group's f32 accumulation
  order is the unsharded fold's;
- the watermark advances from the PRE-filter rows, so every shard's
  cutoff sequence — late drops and evictions — is the unsharded one;
- a batch whose rows are ALL foreign still dispatches empty (offsets
  advance; the slab's per-batch Kahan rewrite count must match);
- tile cell spaces are disjoint across shards (merge is upsert-only);
  positions converge through the store's per-vehicle monotonic guard.
"""

import copy
import json
import time

import numpy as np
import pytest

from heatmap_tpu.config import load_config
from heatmap_tpu.sink import MemoryStore
from heatmap_tpu.stream import MemorySource, MicroBatchRuntime

T_NOW = int(time.time()) - 600
BATCH = 256
N_SHARDS = 3


def mk_stream():
    """Event stream with every hazard the differential must cover:
    clean traffic over a wide box (many distinct cells → all shards),
    invalid rows (dropped identically by every shard — each consumes
    the full stream), duplicates (same vehicle/ts/position → same cell
    → same shard), and late rows a full hour behind the watermark.
    Provider is a function of the vehicle: the positions entity is
    ``provider|vehicleId``, so a vehicle emitting under two providers
    would be two store entities racing one host-side monotonic guard —
    ambiguous even unsharded."""
    rng = np.random.default_rng(11)

    def ev(i, t, lat=None, lon=None):
        v = i % 37
        return {
            "provider": "mbta" if v % 3 else "opensky",
            "vehicleId": f"veh-{v}",
            "lat": float(rng.uniform(42.3, 42.5)) if lat is None else lat,
            "lon": float(rng.uniform(-71.2, -71.0)) if lon is None else lon,
            "speedKmh": float(rng.uniform(0, 80)),
            "bearing": 0.0,
            "accuracyM": 5.0,
            "ts": t,
        }

    out = [ev(i, T_NOW + i % 120) for i in range(3 * BATCH)]
    bad = [
        ev(1, T_NOW + 130, lat=95.0),            # lat out of range
        ev(2, T_NOW + 130, lon=-200.0),          # lon out of range
        ev(3, -5),                               # negative ts
        ev(4, T_NOW + 130, lat=float("nan")),    # non-finite lat
    ]
    dup = ev(0, T_NOW + 200, lat=42.35, lon=-71.05)
    out += bad + [copy.deepcopy(dup) for _ in range(8)]
    out += [ev(i, T_NOW - 3600) for i in range(24)]          # late
    out += [ev(i, T_NOW + 210 + i % 30) for i in range(BATCH - 36)]
    return out


def run_shard(tmp_path, events, store, tag, shards=1, index=0,
              view=None, oversample=1, max_batches=None,
              checkpoint_every=0, source=None, shard_res=-1):
    cfg = load_config(
        {}, batch_size=BATCH, state_capacity_log2=12, speed_hist_bins=8,
        store="memory", emit_flush_k=3, shards=shards, shard_index=index,
        shard_oversample=oversample, shard_res=shard_res,
        checkpoint_dir=str(tmp_path / f"ckpt-{tag}"))
    if source is None:
        source = MemorySource(copy.deepcopy(events))
        source.finish()
    rt = MicroBatchRuntime(cfg, source, store,
                           checkpoint_every=checkpoint_every, view=view)
    rt.run(max_batches=max_batches)
    return rt


def test_one_vs_n_shards_byte_identical(tmp_path):
    events = mk_stream()
    base_store = MemoryStore()
    rt1 = run_shard(tmp_path, events, base_store, "base")

    # N shards, ONE shared store and ONE shared merged view: every
    # shard's writer fans its emits in through the same view-apply hook
    # (cell spaces are disjoint → upsert-only, no conflicts)
    from heatmap_tpu.query import TileMatView

    merged_view = TileMatView(delta_log=4096, pyramid_levels=2)
    fleet_store = MemoryStore()
    fleet = []
    for i in range(N_SHARDS):
        fleet.append(run_shard(tmp_path, events, fleet_store, f"s{i}",
                               shards=N_SHARDS, index=i, view=merged_view))

    # byte-identical merged sink state
    assert base_store._tiles.keys() == fleet_store._tiles.keys()
    assert len(base_store._tiles) > 100  # wide box: a real city's worth
    for k in base_store._tiles:
        assert base_store._tiles[k] == fleet_store._tiles[k], k
    assert base_store._positions == fleet_store._positions
    assert len(base_store._positions) > 0

    # accounting parity: each shard consumes the FULL stream (invalid
    # rows counted per shard), folds only its own (valid/late sum)
    # (positions_emitted is deliberately absent here: each shard's
    # host-side monotonic guard sees only its own rows, so a vehicle
    # crossing shard boundaries emits from several shards — the STORE's
    # per-entity monotonic upsert is what converges them, asserted
    # byte-exactly above)
    c1 = rt1.metrics.counters
    for key in ("events_valid", "events_late", "tiles_emitted"):
        assert sum(rt.metrics.counters.get(key, 0) for rt in fleet) \
            == c1.get(key, 0), key
    for rt in fleet:
        assert rt.metrics.counters.get("events_invalid") \
            == c1.get("events_invalid"), "each shard sees every invalid"
        assert rt.metrics.counters.get("events_out_of_shard", 0) > 0
        # the watermark tracks the FULL stream on every shard
        assert rt.max_event_ts == rt1.max_event_ts

    # merged-view fan-in == the unsharded runtime's own view, doc for
    # doc, across every grid it materialized
    assert set(rt1.matview._grids) == set(merged_view._grids)
    for grid in rt1.matview._grids:
        _, ws1, docs1 = rt1.matview.snapshot(grid)
        _, wsN, docsN = merged_view.snapshot(grid)
        assert ws1 == wsN
        by_cell = lambda docs: {d["cellId"]: d for d in docs}
        assert by_cell(docs1) == by_cell(docsN), grid


def test_all_foreign_batches_still_advance_the_stream(tmp_path):
    """A shard that owns NONE of a batch's cells must still dispatch
    (empty), advance offsets and the watermark, and count the rows as
    out-of-shard — otherwise its checkpoint could never move past
    foreign stretches of the stream and the per-batch slab rewrite
    count would diverge from the unsharded fold's."""
    rng = np.random.default_rng(7)
    # one tight cluster → few parent cells → some shard owns nothing
    events = [{"provider": "p", "vehicleId": f"v{i % 5}",
               "lat": 42.3601 + float(rng.uniform(-1e-4, 1e-4)),
               "lon": -71.0589 + float(rng.uniform(-1e-4, 1e-4)),
               "speedKmh": 1.0, "ts": T_NOW + i} for i in range(2 * BATCH)]
    from heatmap_tpu.stream.shardmap import ShardMap

    sm = ShardMap(4, 0, 8, parent_res=5)
    cells = sm.cells_of(np.radians([42.3601]).astype(np.float32),
                        np.radians([-71.0589]).astype(np.float32))
    owner = int(sm.shard_of_cells(cells)[0])
    loser = (owner + 1) % 4
    store = MemoryStore()
    rt = run_shard(tmp_path, events, store, "loser", shards=4, index=loser,
                   shard_res=5)
    c = rt.metrics.counters
    assert c.get("events_valid", 0) == 0
    assert c.get("events_out_of_shard") == 2 * BATCH
    assert rt.epoch == 2                      # both batches dispatched
    assert rt.source.offset() == 2 * BATCH    # offsets advanced past them
    assert rt.max_event_ts == T_NOW + 2 * BATCH - 1  # full-stream wm
    assert len(store._tiles) == 0


def test_sharded_resume_replays_only_own_offsets(tmp_path):
    """Chaos-convergence half of the supervisor test: a shard killed
    mid-stream resumes from ITS OWN checkpoint namespace
    (<ckpt>/shard<i>), replays only its own offsets, and the merged
    store converges to the single-shard differential baseline."""
    events = mk_stream()
    path = tmp_path / "corpus.jsonl"
    with open(path, "w") as fh:
        for e in events:
            fh.write(json.dumps(e) + "\n")

    from heatmap_tpu.stream.source import JsonlReplaySource

    base_store = MemoryStore()
    run_shard(tmp_path, events, base_store, "rbase",
              source=JsonlReplaySource(str(path)))

    fleet_store = MemoryStore()
    ckpt = tmp_path / "fleet-ckpt"
    cfg_kw = dict(batch_size=BATCH, state_capacity_log2=12,
                  speed_hist_bins=8, store="memory", emit_flush_k=3,
                  shards=2, shard_oversample=1,
                  checkpoint_dir=str(ckpt))

    # shard 0 runs to completion
    cfg0 = load_config({}, shard_index=0, **cfg_kw)
    rt0 = MicroBatchRuntime(cfg0, JsonlReplaySource(str(path)),
                            fleet_store, checkpoint_every=1)
    rt0.run()

    # shard 1 "dies" after 2 batches (bounded run commits through its
    # own close), then a fresh process resumes and finishes
    cfg1 = load_config({}, shard_index=1, **cfg_kw)
    rt1a = MicroBatchRuntime(cfg1, JsonlReplaySource(str(path)),
                             fleet_store, checkpoint_every=1)
    rt1a.run(max_batches=2)
    assert (ckpt / "shard1").is_dir(), "per-shard checkpoint namespace"
    rt1b = MicroBatchRuntime(cfg1, JsonlReplaySource(str(path)),
                             fleet_store, checkpoint_every=1)
    # the resume seeks shard 1's OWN offsets — past what IT dispatched,
    # untouched by shard 0's (further-along) checkpoints
    assert rt1b.source.offset() == rt1a.source.offset()
    assert rt1b.epoch == rt1a.epoch
    rt1b.run()

    assert base_store._tiles.keys() == fleet_store._tiles.keys()
    for k in base_store._tiles:
        assert base_store._tiles[k] == fleet_store._tiles[k], k
    assert base_store._positions == fleet_store._positions


def test_oversample_mode_is_semantically_equivalent(tmp_path):
    """HEATMAP_SHARD_OVERSAMPLE > 1 (the throughput mode: a shard polls
    N feed-batches of stream rows per step and folds only its compacted
    share) re-batches the fold, so f32 bits may differ — but the merged
    integer aggregates and the cell space must be exactly the unsharded
    fold's, and float aggregates equal to fp tolerance."""
    events = mk_stream()[:3 * BATCH]  # clean prefix: no late-boundary
    base_store = MemoryStore()
    run_shard(tmp_path, events, base_store, "obase")
    fleet_store = MemoryStore()
    for i in range(2):
        run_shard(tmp_path, events, fleet_store, f"os{i}", shards=2,
                  index=i, oversample=2)
    assert base_store._tiles.keys() == fleet_store._tiles.keys()
    for k, d1 in base_store._tiles.items():
        dN = fleet_store._tiles[k]
        assert d1["count"] == dN["count"], k
        assert d1["avgSpeedKmh"] == pytest.approx(dN["avgSpeedKmh"],
                                                  rel=1e-5), k


def test_watermark_alignment_holds_cutoff_at_fleet_low_bound(
        tmp_path, monkeypatch):
    """With a supervisor channel attached, a shard's effective cutoff
    is bounded by the slowest FRESH peer's published watermark — and a
    stale straggler drops out of the bound instead of freezing
    eviction fleet-wide."""
    from heatmap_tpu.obs import ENV_CHANNEL
    from heatmap_tpu.obs.xproc import (publish_shard_watermark,
                                       shard_watermark_path,
                                       shard_watermarks_from)

    chan = str(tmp_path / "chan")
    monkeypatch.setenv(ENV_CHANNEL, chan)
    events = [{"provider": "p", "vehicleId": "v0", "lat": 42.36,
               "lon": -71.05, "speedKmh": 1.0, "ts": T_NOW}]
    store = MemoryStore()
    rt = run_shard(tmp_path, events, store, "wm", shards=2, index=0)
    # the shard published its own watermark during the run
    wms = shard_watermarks_from(chan, max_age_s=60.0)
    assert wms.get("shard0") == rt.max_event_ts == T_NOW

    # a fresh straggling peer bounds the effective watermark
    publish_shard_watermark(chan, "shard1", T_NOW - 500)
    rt._shard_wm_read_last = 0.0  # bust the 1 s read cache
    assert rt._effective_max_ts() == T_NOW - 500
    assert rt._g_shard_wm_lag.value == 500

    # a STALE straggler is ignored (a dead shard must not freeze the
    # fleet's eviction forever)
    stale = {"max_event_ts": T_NOW - 9000,
             "updated_unix": time.time() - 3600}
    with open(shard_watermark_path(chan, "shard1"), "w") as fh:
        json.dump(stale, fh)
    rt._shard_wm_read_last = 0.0
    assert rt._effective_max_ts() == T_NOW
    assert rt._g_shard_wm_lag.value == 0


def test_governed_shards_converge_apart_results_identical(tmp_path):
    """ISSUE 10 satellite: two GOVERNED shards under skewed load each
    converge to a different effective batch size (each shard governs
    independently off its own fill/age signals), while the merged
    emits stay byte-identical to the ungoverned fleet — the governor
    re-partitions batching, never results, and the cutoff trajectory
    (watermark, late drops) is untouched.

    The corpus is exact-arithmetic (fixed position per vehicle —
    centroid residuals exactly 0; speeds on a 0.25 grid) so
    byte-identity across REGROUPED batch boundaries is decidable; the
    skew is real (80% of rows land in shard 0's cell space, probed
    through the actual partitioner), and the governors run their OWN
    control law — only the breach signal (event ages over the SLO) is
    scripted, since wall-clock staleness can't be made deterministic
    in-suite."""
    from heatmap_tpu.stream.events import columns_from_arrays
    from heatmap_tpu.stream.shardmap import ShardMap

    # fixed candidate positions, partitioned through the REAL shardmap
    rng = np.random.default_rng(5)
    cand = np.stack([42.30 + rng.uniform(0, 0.2, 48),
                     -71.20 + rng.uniform(0, 0.2, 48)], axis=1)
    sm0 = ShardMap(2, 0, snap_res=8)
    owned0, _, _ = sm0.filter_columns(columns_from_arrays(
        cand[:, 0].astype(np.float32), cand[:, 1].astype(np.float32),
        np.zeros(48, np.float32), np.full(48, T_NOW, np.int64),
        vehicle_id=np.arange(48, dtype=np.int32),
        vehicles=[str(i) for i in range(48)]))
    mine0 = {int(v) for v in owned0.vehicle_id}
    heavy = [i for i in range(48) if i in mine0][:12]
    light = [i for i in range(48) if i not in mine0][:3]
    assert len(heavy) == 12 and len(light) == 3, "probe found both sides"

    def ev(slot, k, t, lat=None, lon=None):
        return {"provider": "p", "vehicleId": f"veh-{slot}",
                "lat": float(cand[slot, 0]) if lat is None else lat,
                "lon": float(cand[slot, 1]) if lon is None else lon,
                "speedKmh": (k % 320) * 0.25, "bearing": 0.0,
                "accuracyM": 5.0, "ts": t}

    events = []
    for k in range(5 * BATCH):
        # 4-of-5 rows to shard 0's cells, 1-of-5 to shard 1's
        slot = heavy[k % 12] if k % 5 else light[k % 3]
        events.append(ev(slot, k, T_NOW + k % 120))
    events.append(ev(heavy[0], 1, T_NOW + 130, lat=95.0))   # invalid
    dup = ev(heavy[1], 7, T_NOW + 200)
    events += [copy.deepcopy(dup) for _ in range(8)]        # dups
    events += [ev(heavy[i % 12], i, T_NOW - 3600)           # very late
               for i in range(24)]

    from heatmap_tpu.query import TileMatView

    def run_fleet(governed):
        store = MemoryStore()
        view = TileMatView(delta_log=4096, pyramid_levels=2)
        rts, srcs = [], []
        for i in range(2):
            cfg = load_config(
                {}, batch_size=BATCH, state_capacity_log2=12,
                speed_hist_bins=8, store="memory", emit_flush_k=1,
                shards=2, shard_index=i, shard_oversample=1,
                govern=governed, govern_min_batch=64,
                govern_interval_s=1e-3,
                checkpoint_dir=str(tmp_path / f"gv{int(governed)}-{i}"))
            src = MemorySource(copy.deepcopy(events))
            src.finish()
            rt = MicroBatchRuntime(cfg, src, store,
                                   checkpoint_every=0, view=view)
            if governed:
                # deterministic control cadence: the governor's clock
                # only advances when the test says an interval elapsed,
                # so each decision covers exactly one known dispatch
                class _Clk:
                    t = 1000.0

                    def __call__(self):
                        return self.t

                rt.governor.clock = _Clk()
                rt.governor._last_decide = rt.governor.clock.t
            rts.append(rt)
            srcs.append(src)
        live = [True, True]
        rounds = 0
        while any(live):
            for i, rt in enumerate(rts):
                if not live[i]:
                    continue
                if governed and rounds < 4:
                    # the scripted HALF of the signal: during the
                    # opening rounds everyone's event age reads over
                    # the SLO (twice, so the interval median dominates
                    # the pipeline's own sub-ms in-suite acks);
                    # fill/idle stay genuinely measured — the law's
                    # divergence comes from the skew, not the script
                    h = rt.metrics.event_age.labels(bound="mean")
                    h.observe(999.0)
                    h.observe(999.0)
                if governed and 1 <= rounds <= 4:
                    # an interval elapses before steps 2..5: each
                    # decision covers the previous full dispatch
                    rt.governor.clock.t += 1.0
                progressed = rt.step_once()
                if not progressed and srcs[i].exhausted:
                    live[i] = False
            rounds += 1
        for rt in rts:
            rt.close()
        return rts, store, view

    rts_g, store_g, _ = run_fleet(True)
    rts_u, store_u, _ = run_fleet(False)

    # each shard converged to ITS OWN batch size: the heavy shard holds
    # the top bucket (fill high — nothing to shrink for), the light
    # shard backed its bucket off to the floor (low fill under breach)
    gov0, gov1 = rts_g[0].governor, rts_g[1].governor
    assert gov0.batch_rows == BATCH, gov0.snapshot()
    assert gov1.batch_rows == 64, gov1.snapshot()
    assert gov0.batch_rows != gov1.batch_rows
    for rt in rts_g:
        assert rt.runtimeinfo.compile.snapshot()[
            "retraces_after_warmup"] == 0

    # ...while the merged results are byte-identical to the ungoverned
    # fleet, and the cutoff trajectory matches (watermark + accounting)
    assert store_g._tiles.keys() == store_u._tiles.keys()
    assert len(store_g._tiles) > 10
    for k in store_g._tiles:
        assert store_g._tiles[k] == store_u._tiles[k], k
    assert store_g._positions == store_u._positions
    for rt_g, rt_u in zip(rts_g, rts_u):
        assert rt_g.max_event_ts == rt_u.max_event_ts
        for key in ("events_valid", "events_late", "events_invalid",
                    "events_out_of_shard"):
            assert rt_g.metrics.counters.get(key, 0) \
                == rt_u.metrics.counters.get(key, 0), key
