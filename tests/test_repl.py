"""Replicated serve fleet (query.repl): delta-log view replication.

The acceptance property is BYTE-INTERCHANGEABILITY: a replica following
the writer's feed serves /api/tiles/latest and /api/tiles/delta?since=0
byte-identical to the writer-fed view — across window advance, staleAt
eviction, and a writer restart (the epoch nonce rejects the stale
tail) — with METRIC-ASSERTED zero store reads in steady state (the
store-scan fallback and rebuild counters stay 0)."""

import datetime as dt
import importlib.util
import json
import os
import tempfile
import time
import urllib.error
import urllib.request

import pytest

from heatmap_tpu import hexgrid
from heatmap_tpu.config import load_config
from heatmap_tpu.query import TileMatView
from heatmap_tpu.query.repl import (
    DeltaLogPublisher,
    FileFeedSource,
    HttpFeedSource,
    ReplicaViewFollower,
    read_meta,
)
from heatmap_tpu.serve import start_background
from heatmap_tpu.sink import MemoryStore
from heatmap_tpu.sink.base import TileDoc, UTC


def _doc(cell, ws, count, speed=30.0, grid="h3r8", ttl_minutes=45):
    return TileDoc("bos", 8, cell, ws, ws + dt.timedelta(minutes=5),
                   count=count, avg_speed_kmh=speed, avg_lat=42.3,
                   avg_lon=-71.05, ttl_minutes=ttl_minutes, grid=grid)


def _cells(n, res=8, lat0=42.30):
    out = []
    for i in range(n * 3):
        c = hexgrid.latlng_to_cell(lat0 + i * 7e-3, -71.05, res)
        if c not in out:
            out.append(c)
        if len(out) == n:
            break
    assert len(out) == n
    return out


def _render(view, grid="h3r8"):
    from heatmap_tpu.serve.api import _features_collection_json

    return _features_collection_json(view.latest_docs(grid)[1])


def _delta_json(view, since, grid="h3r8"):
    return json.dumps(view.delta(grid, since), default=str)


def _drain(pub, fol):
    pub.flush()
    while fol.step():
        pass


# ------------------------------------------------------------ view level
def test_replica_follows_feed_byte_identical_incl_eviction(tmp_path):
    """Writer applies + window advance + latest-window eviction (fake
    clock) all replicate seq-exactly; renders and delta responses are
    byte-identical at every checkpoint."""
    clock = {"t": 1_900_000_000.0}
    w = TileMatView(now_fn=lambda: clock["t"])
    pub = DeltaLogPublisher(w, str(tmp_path), start=False)
    r = TileMatView(replica=True, now_fn=lambda: clock["t"])
    fol = ReplicaViewFollower(r, FileFeedSource(str(tmp_path)))
    base = dt.datetime.fromtimestamp(clock["t"], UTC)
    ws1 = base - dt.timedelta(minutes=10)
    ws2 = base - dt.timedelta(minutes=5)
    cells = _cells(4)

    def check():
        assert r.seq == w.seq
        assert _render(w) == _render(r)
        for since in (0, 1, 2, w.seq):
            assert _delta_json(w, since) == _delta_json(r, since)

    w.apply_docs([_doc(cells[0], ws1, 1, ttl_minutes=6),
                  _doc(cells[1], ws1, 2, ttl_minutes=6)])
    _drain(pub, fol)
    check()
    # same-window update + window advance
    w.apply_docs([_doc(cells[0], ws1, 7, ttl_minutes=6)])
    w.apply_docs([_doc(cells[2], ws2, 3, ttl_minutes=6)])
    _drain(pub, fol)
    check()
    # latest-window eviction: writer's lazy evict advances seq and
    # publishes the marker; the replica applies it instead of running
    # its own clock-driven latest eviction
    clock["t"] += 12 * 60
    w.etag("h3r8")
    _drain(pub, fol)
    check()
    assert w.latest_docs("h3r8")[1] == [] == r.latest_docs("h3r8")[1]
    assert fol.synced and fol.seq_lag() == 0 and fol.healthy()


def test_snapshot_catchup_after_rotation(tmp_path):
    """A follower arriving after the log rotated past seq 0 bootstraps
    from the rotation snapshot, then tails — and lands byte-identical
    with exactly one snapshot load."""
    from heatmap_tpu.obs.registry import Registry

    w = TileMatView()
    pub = DeltaLogPublisher(w, str(tmp_path), seg_bytes=4096, segments=2,
                            start=False)
    ws = dt.datetime.now(UTC).replace(microsecond=0) - \
        dt.timedelta(minutes=2)
    for i in range(60):
        w.apply_docs([_doc(f"8a2a1072b59f{i:03x}", ws, i + 1)])
        pub.flush()
    meta = read_meta(str(tmp_path))
    assert meta["min_seq"] > 1  # seq 1 really is gone from the log
    reg = Registry()
    r = TileMatView(replica=True)
    fol = ReplicaViewFollower(r, FileFeedSource(str(tmp_path)),
                              registry=reg)
    while fol.step():
        pass
    assert r.seq == w.seq == 60
    assert _render(w) == _render(r)
    text = reg.expose_text()
    assert "heatmap_repl_snapshot_loads_total 1" in text
    assert "heatmap_repl_seq_lag 0" in text
    assert "heatmap_repl_synced 1" in text


def test_writer_restart_epoch_rejects_stale_tail(tmp_path):
    """A restarted writer mints a fresh epoch: its publisher sweeps the
    old epoch's artifacts, and a live follower discards EVERYTHING it
    held and re-bootstraps — the old epoch's seqs can never splice
    into the new feed even though both start from 1."""
    import glob as _glob

    w1 = TileMatView()
    pub1 = DeltaLogPublisher(w1, str(tmp_path), start=False)
    ws = dt.datetime.now(UTC).replace(microsecond=0) - \
        dt.timedelta(minutes=2)
    cells = _cells(3)
    w1.apply_docs([_doc(cells[0], ws, 1), _doc(cells[1], ws, 2)])
    pub1.flush()
    r = TileMatView(replica=True)
    fol = ReplicaViewFollower(r, FileFeedSource(str(tmp_path)))
    _drain(pub1, fol)
    assert r.seq == 1 and fol.epoch == pub1.epoch
    pub1.close()
    assert read_meta(str(tmp_path)).get("closed") is True
    # restart: DIFFERENT content, same seq numbers
    w2 = TileMatView()
    pub2 = DeltaLogPublisher(w2, str(tmp_path), start=False)
    w2.apply_docs([_doc(cells[2], ws, 9)])
    pub2.flush()
    # the stale epoch's tail is gone from disk
    assert not [p for p in _glob.glob(str(tmp_path / "seg-*"))
                if pub1.epoch in p]
    fol.step()
    assert fol.epoch == pub2.epoch
    assert r.seq == w2.seq == 1
    assert _render(w2) == _render(r)  # NOT the old epoch's seq-1 state


def test_follower_behind_pruned_horizon_resyncs(tmp_path):
    """A follower that stalls past the retained segments re-bootstraps
    from the snapshot instead of silently skipping the pruned seqs."""
    w = TileMatView()
    pub = DeltaLogPublisher(w, str(tmp_path), seg_bytes=4096, segments=1,
                            start=False)
    ws = dt.datetime.now(UTC).replace(microsecond=0) - \
        dt.timedelta(minutes=2)
    w.apply_docs([_doc("8a2a1072b59f001", ws, 1)])
    pub.flush()
    r = TileMatView(replica=True)
    fol = ReplicaViewFollower(r, FileFeedSource(str(tmp_path)))
    fol.step()
    assert r.seq == 1
    # the follower stalls while the writer churns the log past it
    for i in range(60):
        w.apply_docs([_doc(f"8a2a1072b59f{i:03x}", ws, i + 2)])
        pub.flush()
    assert read_meta(str(tmp_path))["min_seq"] > 2
    with pytest.raises(OSError):
        fol.step()  # detects the horizon overrun...
    fol.step()      # ...and the next round re-bootstraps
    assert r.seq == w.seq
    assert _render(w) == _render(r)


# ----------------------------------------------------- serve integration
def _get(url, hdrs=None):
    req = urllib.request.Request(url)
    for k, v in (hdrs or {}).items():
        req.add_header(k, v)
    with urllib.request.urlopen(req, timeout=10) as r:
        return r.status, dict(r.headers), r.read()


def _wait_synced(httpd, want_seq=None, timeout=15.0):
    fol = httpd.get_app().repl_follower
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fol.synced and fol.seq_lag() == 0 and \
                (want_seq is None or fol.applied == want_seq):
            return fol
        time.sleep(0.02)
    raise AssertionError(
        f"replica never caught up (synced={fol.synced}, "
        f"applied={fol.applied}, want={want_seq})")


class _CountingStore(MemoryStore):
    """MemoryStore that counts read-path calls — the zero-store-read
    assertion is a number, not a log grep."""

    def __init__(self):
        super().__init__()
        self.reads = 0

    def latest_window_start(self, grid=None):
        self.reads += 1
        return super().latest_window_start(grid)

    def tiles_in_window(self, start, grid=None):
        self.reads += 1
        return super().tiles_in_window(start, grid)


def test_http_feed_endpoints_and_remote_follower(tmp_path):
    """The writer-side serve app re-exposes the feed at /api/repl/*;
    a remote follower over the TCP transport lands byte-identical."""
    w = TileMatView()
    pub = DeltaLogPublisher(w, str(tmp_path), start=False)
    ws = dt.datetime.now(UTC).replace(microsecond=0) - \
        dt.timedelta(minutes=2)
    cells = _cells(3)
    w.apply_docs([_doc(c, ws, i + 1) for i, c in enumerate(cells)])
    pub.flush()
    cfg = load_config({}, serve_port=0, repl_dir=str(tmp_path))
    httpd, _t, port = start_background(MemoryStore(), cfg, port=0)
    base = f"http://127.0.0.1:{port}"
    try:
        _, _, b = _get(base + "/api/repl/meta")
        meta = json.loads(b)
        assert meta["epoch"] == pub.epoch and meta["last_seq"] == 1
        _, _, b = _get(base + f"/api/repl/snapshot?epoch={pub.epoch}")
        assert json.loads(b)["epoch"] == pub.epoch
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(base + "/api/repl/snapshot?epoch=deadbeef")
        assert ei.value.code == 404
        r = TileMatView(replica=True)
        fol = ReplicaViewFollower(r, HttpFeedSource(base))
        while fol.step():
            pass
        assert r.seq == w.seq
        assert _render(w) == _render(r)
    finally:
        httpd.shutdown()
    # without a feed dir the endpoints answer 503, not garbage
    httpd2, _t2, port2 = start_background(
        MemoryStore(), load_config({}, serve_port=0), port=0)
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(f"http://127.0.0.1:{port2}/api/repl/meta")
        assert ei.value.code == 503
    finally:
        httpd2.shutdown()


def _mini_runtime(tmpdir, events, **cfg_over):
    from heatmap_tpu.stream import MicroBatchRuntime
    from heatmap_tpu.stream.source import MemorySource

    cfg = load_config({}, batch_size=16, state_capacity_log2=8,
                      speed_hist_bins=4, store="memory", serve_port=0,
                      checkpoint_dir=tempfile.mkdtemp(dir=tmpdir),
                      **cfg_over)
    src = MemorySource(events)
    st = MemoryStore()
    rt = MicroBatchRuntime(cfg, src, st, checkpoint_every=0)
    return cfg, src, st, rt


def _evs(n, t0, lat0=42.0):
    return [{"provider": "p", "vehicleId": f"v{i}", "lat": lat0 + i * 1e-3,
             "lon": -71.0, "speedKmh": 10.0 + i, "ts": t0 + i}
            for i in range(n)]


def test_runtime_replica_differential_with_writer_restart(tmp_path):
    """ACCEPTANCE: a replica following the streaming writer's feed
    serves /api/tiles/latest + /api/tiles/delta?since=0 byte-identical
    to the writer, across window advance AND a writer restart — and
    with zero store reads (the replica's store counts ZERO read calls;
    its fallback/rebuild counters stay 0)."""
    feed = tempfile.mkdtemp(dir=str(tmp_path))
    t0 = int(time.time()) - 900
    cfg, src, st, rt = _mini_runtime(str(tmp_path), [], repl_dir=feed)
    w_httpd, _t, w_port = start_background(st, cfg, runtime=rt, port=0)
    r_store = _CountingStore()
    cfg_r = load_config({}, serve_port=0, repl_feed=feed,
                        repl_poll_ms=20)
    r_httpd, _t2, r_port = start_background(r_store, cfg_r, port=0)

    def compare():
        for path in ("/api/tiles/latest", "/api/tiles/delta?since=0"):
            _, _, a = _get(f"http://127.0.0.1:{w_port}{path}")
            _, _, b = _get(f"http://127.0.0.1:{r_port}{path}")
            assert a == b, f"replica diverged on {path}"
        return a

    try:
        # segment 1 + a segment crossing into a NEW 5-min window
        for seg, (n, ts) in enumerate([(32, t0), (32, t0 + 600)]):
            src.push(_evs(n, ts, lat0=42.0 + seg * 0.01))
            while rt.step_once():
                pass
            rt.flush_pending()
            rt.writer.drain()
            rt.repl_pub.flush()
            _wait_synced(r_httpd, want_seq=rt.matview.seq)
            body = compare()
            assert json.loads(body)["features"]
        # ---- writer restart: new runtime (fresh view + epoch), same
        # durable store; the replica must discard the old epoch and
        # converge on the new writer's state
        rt.close()
        w_httpd.shutdown()
        cfg2, src2, _st2, rt2 = _mini_runtime(str(tmp_path), [],
                                              repl_dir=feed)
        rt2.store = st  # same durable store
        rt2.writer.store = st
        w_httpd2, _t3, w_port2 = start_background(st, cfg2, runtime=rt2,
                                                  port=0)
        w_port = w_port2
        try:
            src2.push(_evs(24, t0 + 660, lat0=42.05))
            while rt2.step_once():
                pass
            rt2.flush_pending()
            rt2.writer.drain()
            rt2.repl_pub.flush()
            fol = _wait_synced(r_httpd, want_seq=rt2.matview.seq)
            assert fol.epoch == rt2.repl_pub.epoch
            body = compare()
            assert json.loads(body)["features"]
        finally:
            w_httpd2.shutdown()
            rt2.close()
        # ---- metric-asserted zero store reads on the replica
        assert r_store.reads == 0
        _, _, mt = _get(f"http://127.0.0.1:{r_port}/metrics")
        text = mt.decode()
        assert "heatmap_repl_fallback_total 0" in text
        assert "heatmap_view_rebuilds_total 0" in text
        assert "heatmap_repl_synced 1" in text
        # and the replica's healthz is green with the repl checks in it
        _, _, hz = _get(f"http://127.0.0.1:{r_port}/healthz")
        payload = json.loads(hz)
        assert payload["status"] == "ok"
        assert payload["checks"]["repl_synced"]["ok"] is True
        assert payload["checks"]["repl_lag_s"]["ok"] is True
    finally:
        r_httpd.shutdown()
        r_httpd.get_app().close_repl()


def test_replica_sse_and_topk_work_from_feed(tmp_path):
    """The replica's whole serving surface runs off the feed: SSE
    pushes fire when the follower applies new records (no store
    polling), and topk serves from the replicated view."""
    import socket

    w = TileMatView()
    pub = DeltaLogPublisher(w, str(tmp_path), flush_s=0.02)
    ws = dt.datetime.now(UTC).replace(microsecond=0) - \
        dt.timedelta(minutes=2)
    cells = _cells(3)
    w.apply_docs([_doc(cells[0], ws, 5)])
    cfg_r = load_config({}, serve_port=0, repl_feed=str(tmp_path),
                        repl_poll_ms=20,
                        sse_heartbeat_s=0.3)
    httpd, _t, port = start_background(MemoryStore(), cfg_r, port=0)
    try:
        # pin the seq: the boot snapshot alone (seq 0) already counts
        # as synced, and an SSE client admitted before the first apply
        # lands would see an extra empty full-sync event
        _wait_synced(httpd, want_seq=w.seq)
        sk = socket.create_connection(("127.0.0.1", port), timeout=10)
        sk.sendall(b"GET /api/tiles/stream?since=0 HTTP/1.0\r\n\r\n")
        sk.settimeout(10)
        buf = b""
        while buf.count(b"event: tiles") < 1:
            buf += sk.recv(65536)
        assert b'"mode": "full"' in buf
        w.apply_docs([_doc(cells[1], ws, 9)])  # writer side
        while buf.count(b"event: tiles") < 2:
            buf += sk.recv(65536)
        assert cells[1].encode() in buf
        sk.close()
        _, _, b = _get(f"http://127.0.0.1:{port}/api/tiles/topk?k=1")
        top = json.loads(b)["features"]
        assert len(top) == 1
        assert top[0]["properties"]["cellId"] == cells[1]  # count 9 > 5
        _, _, b = _get(f"http://127.0.0.1:{port}/debug/view")
        dv = json.loads(b)
        assert dv["mode"] == "replica" and dv["repl"]["synced"]
    finally:
        httpd.shutdown()
        httpd.get_app().close_repl()
        pub.close()


def test_unsynced_replica_falls_back_counted_and_degraded(tmp_path):
    """A replica whose feed never materializes serves the store through
    the DEMOTED fallback: content still flows, the fallback counter
    moves, and /healthz reports degraded (repl_synced false) — never
    ok-but-empty."""
    st = MemoryStore()
    ws = dt.datetime.now(UTC).replace(microsecond=0) - \
        dt.timedelta(minutes=2)
    cells = _cells(2)
    st.upsert_tiles([_doc(cells[0], ws, 3)])
    empty_feed = tempfile.mkdtemp(dir=str(tmp_path))  # no meta ever
    cfg = load_config({}, serve_port=0, repl_feed=empty_feed,
                      repl_poll_ms=20)
    httpd, _t, port = start_background(st, cfg, port=0)
    try:
        _, _, b = _get(f"http://127.0.0.1:{port}/api/tiles/latest")
        fc = json.loads(b)
        assert {f["properties"]["cellId"] for f in fc["features"]} == \
            {cells[0]}
        _, _, hz = _get(f"http://127.0.0.1:{port}/healthz")
        payload = json.loads(hz)
        assert payload["status"] == "degraded"
        assert payload["checks"]["repl_synced"]["ok"] is False
        _, _, mt = _get(f"http://127.0.0.1:{port}/metrics")
        text = mt.decode()
        lines = [ln for ln in text.splitlines()
                 if ln.startswith("heatmap_repl_fallback_total")]
        assert lines and float(lines[0].split()[-1]) > 0
    finally:
        httpd.shutdown()
        httpd.get_app().close_repl()


def test_failed_seed_scan_keeps_healthz_degraded_until_catchup():
    """r9 satellite: a serve-only worker whose initial store scan fails
    must answer /healthz degraded (not ok-but-empty) and retry with
    backoff; the first successful rebuild clears the check."""
    class FlakyStore(MemoryStore):
        def __init__(self):
            super().__init__()
            self.fail = True

        def latest_window_start(self, grid=None):
            if self.fail:
                raise IOError("injected boot-time store outage")
            return super().latest_window_start(grid)

    st = FlakyStore()
    ws = dt.datetime.now(UTC).replace(microsecond=0) - \
        dt.timedelta(minutes=2)
    (cell,) = _cells(1)
    st.fail = False
    st.upsert_tiles([_doc(cell, ws, 4)])
    st.fail = True
    cfg = load_config({"HEATMAP_VIEW_POLL_MS": "40"}, serve_port=0)
    httpd, _t, port = start_background(st, cfg, port=0)
    try:
        _, _, b = _get(f"http://127.0.0.1:{port}/api/tiles/latest")
        assert json.loads(b)["features"] == []  # store down: empty...
        _, _, hz = _get(f"http://127.0.0.1:{port}/healthz")
        payload = json.loads(hz)
        # ...but NOT ok-but-empty: the catch-up check is failing
        assert payload["status"] == "degraded"
        assert payload["checks"]["view_catchup"]["ok"] is False
        st.fail = False
        deadline = time.time() + 10
        while time.time() < deadline:
            _, _, b = _get(f"http://127.0.0.1:{port}/api/tiles/latest")
            if json.loads(b)["features"]:
                break
            time.sleep(0.05)  # backoff retry: recovers without a poke
        assert json.loads(b)["features"]
        _, _, hz = _get(f"http://127.0.0.1:{port}/healthz")
        payload = json.loads(hz)
        assert payload["status"] == "ok"
        assert payload["checks"]["view_catchup"]["ok"] is True
    finally:
        httpd.shutdown()


# -------------------------------------------------------- fleet surfaces
def test_fleet_healthz_degrades_on_unsynced_replica(tmp_path,
                                                    monkeypatch):
    """The replica's member snapshot carries its serve-tier healthz
    verdict, so /fleet/healthz degrades NAMING the lagging/unsynced
    replica without scraping it."""
    from heatmap_tpu.obs.fleet import FleetAggregator
    from heatmap_tpu.obs.xproc import ENV_CHANNEL
    from heatmap_tpu.serve.api import ServeFleetMember, make_wsgi_app

    chan = str(tmp_path / "chan.json")
    monkeypatch.setenv(ENV_CHANNEL, chan)
    empty_feed = tempfile.mkdtemp(dir=str(tmp_path))
    cfg = load_config({}, serve_port=0, repl_feed=empty_feed,
                      repl_poll_ms=20)
    app = make_wsgi_app(MemoryStore(), cfg)
    member = ServeFleetMember(app.serve_registry, chan, tag="replica0",
                              healthz_fn=app.healthz_fn)
    try:
        member.publish()
        agg = FleetAggregator(chan)
        payload, down = agg.healthz()
        assert not down
        assert payload["status"] == "degraded"
        chk = payload["checks"]["member_replica0"]
        assert chk["ok"] is False
        assert "repl_synced" in chk.get("failing", [])
        # and the federated exposition carries the replica's lag gauge
        # under its proc label (what obs_top --fleet renders)
        text = agg.metrics_text()
        assert 'heatmap_repl_synced{proc="replica0"} 0' in text
    finally:
        app.close_repl()


def _load_tool(name):
    repo = os.path.abspath(os.path.join(os.path.dirname(__file__),
                                        os.pardir))
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(repo, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_obs_top_fleet_renders_serve_replica_rows():
    """obs_top --fleet: serve-role members get the replica table —
    seq lag, SSE clients, 304 ratio — plus the fleet max-lag line."""
    top = _load_tool("obs_top")
    text = """\
heatmap_fleet_members 2
heatmap_fleet_member_up{proc="serve1",role="serve"} 1
heatmap_fleet_member_up{proc="serve2",role="serve"} 1
heatmap_repl_seq_lag{proc="serve1"} 0
heatmap_repl_seq_lag{proc="serve2"} 7
heatmap_serve_sse_clients{proc="serve1"} 12
heatmap_serve_sse_clients{proc="serve2"} 3
heatmap_serve_304_total{proc="serve1",endpoint="tiles"} 75
heatmap_serve_renders_total{proc="serve1",endpoint="tiles"} 25
heatmap_serve_renders_total{proc="serve2",endpoint="tiles"} 10
"""
    m = top.parse_prom(text)
    frame = top.render_fleet_frame(m, None, 0.0, {"status": "ok",
                                                  "checks": {}})
    assert "serve1" in frame and "serve2" in frame
    assert "75.0 %" in frame      # serve1: 75 of 100 answered 304
    assert "0.0 %" in frame       # serve2: renders only
    assert "repl max seq lag 7" in frame
    assert "replicas 2" in frame


def test_repl_stamp_reads_member_lag(tmp_path, monkeypatch):
    from heatmap_tpu.obs.fleet import repl_stamp
    from heatmap_tpu.obs.xproc import ENV_CHANNEL, publish_member_snapshot

    chan = str(tmp_path / "chan.json")
    monkeypatch.setenv(ENV_CHANNEL, chan)
    assert repl_stamp() == {}  # nothing on the channel yet
    publish_member_snapshot(
        chan, "serve1", role="serve",
        metrics_text="# TYPE heatmap_repl_seq_lag gauge\n"
                     "heatmap_repl_seq_lag 0\n")
    publish_member_snapshot(
        chan, "serve2", role="serve",
        metrics_text="# TYPE heatmap_repl_seq_lag gauge\n"
                     "heatmap_repl_seq_lag 5\n")
    publish_member_snapshot(chan, "p0", role="runtime",
                            metrics_text="")  # no follower: not counted
    assert repl_stamp() == {"repl": {"replicas": 2, "max_seq_lag": 5}}


def test_stale_feed_keeps_serving_replicated_state(tmp_path, monkeypatch):
    """r8 review finding pinned: once a replica has synced, a feed
    going dark must NOT trigger the store-scan fallback — with the
    zero-store-read topology (empty store) a fallback scan would WIPE
    the replicated view to empty.  The replica keeps serving the last
    replicated state (bounded-stale) and /healthz degrades on feed
    age; the fallback/rebuild counters stay 0."""
    import shutil

    from heatmap_tpu.obs.xproc import ENV_FLEET_MAX_AGE

    monkeypatch.setenv(ENV_FLEET_MAX_AGE, "0.3")
    feed = tempfile.mkdtemp(dir=str(tmp_path))
    w = TileMatView()
    pub = DeltaLogPublisher(w, feed, start=False)
    ws = dt.datetime.now(UTC).replace(microsecond=0) - \
        dt.timedelta(minutes=2)
    (cell,) = _cells(1)
    w.apply_docs([_doc(cell, ws, 6)])
    pub.flush()
    cfg = load_config({}, serve_port=0, repl_feed=feed, repl_poll_ms=20)
    httpd, _t, port = start_background(MemoryStore(), cfg, port=0)
    try:
        _wait_synced(httpd, want_seq=1)
        # the writer vanishes: no heartbeats, meta gone
        shutil.rmtree(feed)
        deadline = time.time() + 10
        while time.time() < deadline:
            _, _, hz = _get(f"http://127.0.0.1:{port}/healthz")
            if json.loads(hz)["status"] == "degraded":
                break
            time.sleep(0.05)
        payload = json.loads(hz)
        assert payload["status"] == "degraded"
        # content survives: the replicated state keeps serving
        _, _, b = _get(f"http://127.0.0.1:{port}/api/tiles/latest")
        assert {f["properties"]["cellId"]
                for f in json.loads(b)["features"]} == {cell}
        _, _, mt = _get(f"http://127.0.0.1:{port}/metrics")
        text = mt.decode()
        assert "heatmap_repl_fallback_total 0" in text
        assert "heatmap_view_rebuilds_total 0" in text
    finally:
        httpd.shutdown()
        httpd.get_app().close_repl()
        pub.close()


# ------------------------------------------------------------ soak smoke
def test_bench_serve_soak_smoke():
    """A miniature --soak run completes green: replicas sync, lag stays
    inside the SLO, and the zero-store-read counters hold at 0."""
    bench = _load_tool("bench_serve")
    out = bench.run_soak(n_tiles=60, replicas=2, clients=40,
                         duration_s=1.5, workers=4, sse_n=2,
                         mutate_ms=200.0)
    assert out["replicas"] == 2 and out["replicas_synced"] == 2
    assert out["requests"] > 0 and out["errors"] == 0
    assert out["sse_events"] >= 2          # every SSE got its full sync
    assert out["zero_store_reads"] is True
    assert out["store_scan_fallbacks"] == 0 and out["view_rebuilds"] == 0
    assert out["repl_lag_ok"] is True
    assert out["max_repl_lag_s"] <= out["slo_repl_lag_s"]
    assert out["p99_ms"] > 0 and out["bytes_sent_wire"] > 0


# ------------------------------------------------------------ config
def test_repl_config_validation():
    with pytest.raises(ValueError):
        load_config({}, repl_seg_bytes=100)
    with pytest.raises(ValueError):
        load_config({}, repl_segments=0)
    with pytest.raises(ValueError):
        load_config({}, repl_poll_ms=1)
    cfg = load_config({"HEATMAP_REPL_DIR": "/tmp/x",
                       "HEATMAP_REPL_FEED": "http://h:1",
                       "HEATMAP_REPL_SEG_BYTES": "8192",
                       "HEATMAP_REPL_SEGMENTS": "3",
                       "HEATMAP_REPL_POLL_MS": "100"})
    assert (cfg.repl_dir, cfg.repl_feed) == ("/tmp/x", "http://h:1")
    assert (cfg.repl_seg_bytes, cfg.repl_segments,
            cfg.repl_poll_ms) == (8192, 3, 100)


def test_obs_top_fleet_renders_serve_wire_rows():
    """obs_top --fleet (ISSUE 14): workers serving the wire path get a
    serve-wire table — negotiated-format mix, wire/rendered byte
    rates, admission sheds, SSE send-queue high-water."""
    top = _load_tool("obs_top")
    base = """\
heatmap_fleet_members 2
heatmap_fleet_member_up{proc="serve1",role="serve"} 1
heatmap_fleet_member_up{proc="serve2",role="serve"} 1
heatmap_repl_seq_lag{proc="serve1"} 0
heatmap_repl_seq_lag{proc="serve2"} 0
heatmap_serve_sse_clients{proc="serve1"} 5
heatmap_serve_sse_clients{proc="serve2"} 2
heatmap_serve_wire_format_total{proc="serve1",endpoint="delta",fmt="bin"} 90
heatmap_serve_wire_format_total{proc="serve1",endpoint="delta",fmt="json"} 10
heatmap_serve_wire_format_total{proc="serve2",endpoint="tiles",fmt="json"} 40
heatmap_serve_shed_total{proc="serve1",endpoint="delta"} 3
heatmap_sse_queue_highwater{proc="serve1"} 7
heatmap_serve_sent_bytes_total{proc="serve1",endpoint="delta"} 1000
heatmap_serve_rendered_bytes_total{proc="serve1",endpoint="delta"} 5000
"""
    prev = top.parse_prom(base)
    cur = top.parse_prom(base.replace(
        'heatmap_serve_sent_bytes_total{proc="serve1",endpoint="delta"}'
        ' 1000',
        'heatmap_serve_sent_bytes_total{proc="serve1",endpoint="delta"}'
        ' 21000').replace(
        'heatmap_serve_rendered_bytes_total{proc="serve1",'
        'endpoint="delta"} 5000',
        'heatmap_serve_rendered_bytes_total{proc="serve1",'
        'endpoint="delta"} 105000'))
    frame = top.render_fleet_frame(cur, prev, 2.0,
                                   {"status": "ok", "checks": {}})
    assert "serve wire" in frame
    # serve1: 90 of 100 responses negotiated binary
    assert "90 %" in frame
    # serve2: JSON only
    assert "0 %" in frame
    # rates off the 2 s delta: (21000-1000)/2 and (105000-5000)/2
    assert "10,000" in frame and "50,000" in frame
    assert "7" in frame   # queue high-water
    lines = [ln for ln in frame.splitlines() if "serve1" in ln]
    assert any("3" in ln for ln in lines)  # shed count rendered
